"""Checkpoint/resume subsystem tests (SURVEY.md §5.4: the durability layer
the reference lacks entirely — these test the journal replay, memory
snapshot, and train-state checkpoint paths)."""

import asyncio
import json

import numpy as np
import pytest

from pilottai_tpu.checkpoint import (
    TaskJournal,
    TrainCheckpointer,
    restore_memory,
    save_memory,
)
from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.core.task import Task, TaskResult, TaskStatus
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.memory.semantic import EnhancedMemory
from pilottai_tpu.serve import Serve


# ----------------------------- journal ---------------------------------- #

def test_journal_roundtrip(tmp_path):
    path = tmp_path / "tasks.jsonl"
    journal = TaskJournal(path)
    done = Task(description="done work")
    pending = Task(description="pending work")
    journal.record_task(done)
    journal.record_task(pending)
    done.mark_completed(TaskResult(success=True, output="ok"))
    journal.record_status(done)
    journal.close()

    tasks = TaskJournal.replay(path)
    assert set(tasks) == {done.id, pending.id}
    assert tasks[done.id].status == TaskStatus.COMPLETED
    assert tasks[done.id].result.output == "ok"
    still_open = TaskJournal.pending(tasks)
    assert [t.id for t in still_open] == [pending.id]


def test_journal_tolerates_torn_line(tmp_path):
    path = tmp_path / "tasks.jsonl"
    journal = TaskJournal(path)
    task = Task(description="survives")
    journal.record_task(task)
    journal.close()
    with open(path, "a") as fh:
        fh.write('{"ev": "task", "ts": 1, "data": {"descrip')  # torn write
    tasks = TaskJournal.replay(path)
    assert list(tasks) == [task.id]


def test_journal_compaction_drops_terminal(tmp_path):
    path = tmp_path / "tasks.jsonl"
    journal = TaskJournal(path)
    keep = Task(description="live")
    drop = Task(description="finished")
    journal.record_task(keep)
    journal.record_task(drop)
    drop.mark_completed(TaskResult(success=True))
    journal.record_status(drop)
    retained = journal.compact()
    assert retained == 1
    tasks = TaskJournal.replay(path)
    assert list(tasks) == [keep.id]
    # Journal still writable after compaction (file handle reopened).
    journal.record_task(Task(description="post-compact"))
    journal.close()
    assert len(TaskJournal.replay(path)) == 2


@pytest.mark.asyncio
async def test_serve_recovers_journaled_tasks(tmp_path):
    """Simulated crash: serve #1 journals queued tasks and dies without
    executing them; serve #2 on the same journal replays and runs them."""
    journal_path = str(tmp_path / "serve.jsonl")

    crashed = Serve(
        name="crashed",
        config=ServeConfig(journal_path=journal_path, decomposition_enabled=False),
    )
    # add_task journals via _queue_task; never started → never executed.
    submitted = [await crashed.add_task(f"recover me {i}") for i in range(3)]
    crashed.journal.close()

    agent = BaseAgent(
        config=AgentConfig(role="processor"),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
    )
    revived = Serve(
        name="revived",
        agents=[agent],
        config=ServeConfig(
            journal_path=journal_path, decomposition_enabled=False,
            task_timeout=30,
        ),
    )
    await revived.start()
    try:
        results = await asyncio.gather(
            *[revived.wait_for(t.id, timeout=30) for t in submitted]
        )
        assert all(r.success for r in results)
        assert revived.metrics["tasks_completed"] == 3
    finally:
        await revived.stop()

    # Post-recovery journal reflects the completions for the *next* boot.
    final = TaskJournal.replay(journal_path)
    assert all(
        final[t.id].status == TaskStatus.COMPLETED
        for t in submitted if t.id in final
    )


@pytest.mark.asyncio
async def test_serve_recovery_skips_completed(tmp_path):
    journal_path = str(tmp_path / "serve.jsonl")
    journal = TaskJournal(journal_path)
    done = Task(description="already done")
    journal.record_task(done)
    done.mark_completed(TaskResult(success=True, output=42))
    journal.record_status(done)
    journal.close()

    serve = Serve(
        name="skip",
        config=ServeConfig(journal_path=journal_path, decomposition_enabled=False),
    )
    await serve.start()
    try:
        assert serve.metrics["tasks_received"] == 0
        assert done.id in serve.completed_tasks
        assert serve.get_result(done.id).output == 42
        assert len(serve.task_queue) == 0
    finally:
        await serve.stop()


# ------------------------- memory snapshot ------------------------------ #

class _FakeEmbedder:
    """Deterministic embedder: hash of text → one-hot-ish unit vector."""

    dim = 8

    def encode_one(self, text: str) -> np.ndarray:
        rng = np.random.default_rng(abs(hash(text)) % (2**32))
        v = rng.normal(size=self.dim).astype(np.float32)
        return v / np.linalg.norm(v)


@pytest.mark.asyncio
async def test_memory_snapshot_roundtrip(tmp_path):
    memory = EnhancedMemory(capacity=100)
    await memory.store_semantic("alpha report", data={"k": 1}, tags={"report"})
    await memory.store_semantic("beta summary", priority=5)
    await memory.store_task("t1", {"phase": "extract"})
    await memory.log_interaction("a1", "a2", {"msg": "hello"})
    await memory.store_pattern("greeting", "hello world")

    await save_memory(memory, tmp_path / "mem")

    restored = EnhancedMemory(capacity=100)
    assert await restore_memory(restored, tmp_path / "mem")
    hits = await restored.keyword_search("alpha")
    assert len(hits) == 1 and hits[0]["data"] == {"k": 1}
    assert (await restored.get_task_history("t1"))[0]["phase"] == "extract"
    assert (await restored.get_interactions("a1"))[0]["payload"] == {"msg": "hello"}
    assert await restored.get_pattern("greeting") == "hello world"
    # New stores keep allocating fresh ids after restore.
    new_id = await restored.store_semantic("gamma")
    assert new_id not in {h["id"] for h in hits}


@pytest.mark.asyncio
async def test_memory_snapshot_preserves_vectors(tmp_path):
    embedder = _FakeEmbedder()
    memory = EnhancedMemory(embedder=embedder, capacity=64)
    await memory.store_semantic("quarterly finance report")
    await memory.store_semantic("vacation photo album")

    await save_memory(memory, tmp_path / "mem")

    restored = EnhancedMemory(embedder=embedder, capacity=64)
    assert await restore_memory(restored, tmp_path / "mem")
    # Semantic search works against restored vectors (no re-embedding).
    hits = await restored.semantic_search("quarterly finance report", limit=1)
    assert hits and hits[0]["text"] == "quarterly finance report"

    # Restore into a memory with no snapshot dir → False.
    assert not await restore_memory(EnhancedMemory(), tmp_path / "nope")


# ------------------------- train checkpoints ---------------------------- #

def _tiny_state():
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt = optax.adam(1e-3)
    return params, opt.init(params), opt


def test_train_checkpointer_roundtrip(tmp_path):
    import jax

    params, opt_state, opt = _tiny_state()
    ckpt = TrainCheckpointer(tmp_path / "train", max_to_keep=2)
    assert ckpt.latest_step() is None

    mutated = jax.tree.map(lambda x: x + 1.0, params)
    ckpt.save(10, (mutated, opt_state))
    ckpt.save(20, (params, opt_state))
    ckpt.save(30, (mutated, opt_state))
    assert ckpt.all_steps() == [20, 30]  # retention pruned step 10
    assert ckpt.latest_step() == 30

    template = (params, opt.init(params))
    (restored_params, restored_opt), step = ckpt.restore(template)
    assert step == 30
    assert np.allclose(np.asarray(restored_params["w"]), 2.0)
    # Optax NamedTuple structure preserved via template.
    assert type(restored_opt) is type(opt_state)

    (p20, _), step = ckpt.restore(template, step=20)
    assert step == 20 and np.allclose(np.asarray(p20["w"]), 1.0)


@pytest.mark.asyncio
async def test_recovery_requeues_parent_with_missing_children(tmp_path):
    """Crash mid-decomposition: parent journaled with subtask ids whose
    records never landed → parent re-runs from scratch instead of
    aggregating a vacuous empty-children success."""
    journal_path = str(tmp_path / "serve.jsonl")
    journal = TaskJournal(journal_path)
    parent = Task(description="decompose me")
    parent.subtasks = ["ghost-child-1", "ghost-child-2"]
    parent.status = TaskStatus.BLOCKED
    journal.record_task(parent)
    journal.close()

    agent = BaseAgent(
        config=AgentConfig(role="processor"),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
    )
    serve = Serve(
        name="reparent", agents=[agent],
        config=ServeConfig(
            journal_path=journal_path, decomposition_enabled=False,
            task_timeout=30,
        ),
    )
    await serve.start()
    try:
        result = await serve.wait_for(parent.id, timeout=30)
        assert result.success
        assert result.output != []  # not a vacuous empty aggregation
    finally:
        await serve.stop()


def test_compaction_keeps_terminal_children_of_live_parent(tmp_path):
    journal = TaskJournal(tmp_path / "j.jsonl")
    parent = Task(description="parent")
    child_done = Task(description="child A", parent_task_id=parent.id)
    child_open = Task(description="child B", parent_task_id=parent.id)
    parent.subtasks = [child_done.id, child_open.id]
    parent.status = TaskStatus.BLOCKED
    for t in (parent, child_done, child_open):
        journal.record_task(t)
    child_done.mark_completed(TaskResult(success=True, output="A out"))
    journal.record_status(child_done)
    retained = journal.compact()
    journal.close()
    assert retained == 3  # completed child kept: its output feeds the parent
    tasks = TaskJournal.replay(tmp_path / "j.jsonl")
    assert tasks[child_done.id].result.output == "A out"


@pytest.mark.asyncio
async def test_wait_for_resolves_recovered_terminal_task(tmp_path):
    journal_path = str(tmp_path / "serve.jsonl")
    journal = TaskJournal(journal_path)
    done = Task(description="finished long ago")
    journal.record_task(done)
    done.mark_completed(TaskResult(success=True, output="cached"))
    journal.record_status(done)
    journal.close()

    serve = Serve(
        name="waiter",
        config=ServeConfig(journal_path=journal_path, decomposition_enabled=False),
    )
    await serve.start()
    try:
        result = await asyncio.wait_for(serve.wait_for(done.id), timeout=2)
        assert result.output == "cached"
    finally:
        await serve.stop()
    # stop() closed the journal; a second start/stop cycle reopens it.
    await serve.start()
    await serve.stop()


@pytest.mark.asyncio
async def test_memory_import_clears_stale_vectors(tmp_path):
    embedder = _FakeEmbedder()
    vectored = EnhancedMemory(embedder=embedder, capacity=16)
    await vectored.store_semantic("old embedded entry")

    plain = EnhancedMemory(capacity=16)  # snapshot with NO vectors
    await plain.store_semantic("restored plain entry")
    await save_memory(plain, tmp_path / "mem")

    assert await restore_memory(vectored, tmp_path / "mem")
    # Old buffer must not score new ids; falls back to keyword search.
    hits = await vectored.semantic_search("restored plain entry")
    assert [h["text"] for h in hits] == ["restored plain entry"]


def test_train_gc_never_deletes_rollback_save(tmp_path):
    params, opt_state, _ = _tiny_state()
    ckpt = TrainCheckpointer(tmp_path / "train", max_to_keep=3)
    for s in (200, 300, 400):
        ckpt.save(s, (params, opt_state))
    ckpt.save(150, (params, opt_state))  # rollback-resume below retained set
    assert 150 in ckpt.all_steps()       # just-saved step survives GC
    assert ckpt.latest_step() == 150
    template = (params, opt_state)
    _, step = ckpt.restore(template)
    assert step == 150


def test_train_checkpointer_latest_survives_marker_loss(tmp_path):
    params, opt_state, _ = _tiny_state()
    ckpt = TrainCheckpointer(tmp_path / "train")
    ckpt.save(5, (params, opt_state))
    (ckpt.root / "LATEST").unlink()
    assert ckpt.latest_step() == 5  # falls back to directory scan
