"""Global KV cache tier (engine/kvcache/, ISSUE 10).

The tier's contract mirrors every other admission fast path's: it
changes WHERE prompt K/V comes from (device hot store → host-RAM cold
tier → recompute), never WHAT gets generated — greedy output with the
tier enabled must be byte-identical to a cold engine's, across
dense/paged caches x speculation on/off, through spill → evict → resume
cycles and through PR 8 recovery landing mid-restore.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.kvcache import HostTier, RadixTree
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.reliability import global_injector
from pilottai_tpu.utils.metrics import global_metrics

KV = (
    "lookups", "hits", "host_hits", "spills", "restores",
    "prefill_tokens_saved", "evictions",
)


def _kv_counters():
    return {k: global_metrics.get(f"engine.kvcache.{k}") for k in KV}


def _kv_delta(before):
    return {
        k: global_metrics.get(f"engine.kvcache.{k}") - before[k] for k in KV
    }


# --------------------------------------------------------------------- #
# Radix tree
# --------------------------------------------------------------------- #

def test_radix_insert_match_remove():
    t = RadixTree()
    a = tuple(range(10, 30))
    b = tuple(range(10, 25))          # proper prefix of a
    c = (10, 11, 99, 98)              # diverges at depth 2
    t.insert(a, "A")
    t.insert(b, "B")
    t.insert(c, "C")
    assert len(t) == 3 and t.has(a) and t.get(b) == "B"
    # Longest proper prefix wins; exact-length match is rejected.
    assert t.longest_payload_prefix(list(a) + [1]).payload == "A"
    assert t.longest_payload_prefix(list(a)).payload == "B"
    assert t.longest_payload_prefix(list(b)) is None
    assert t.longest_payload_prefix([10, 11, 99, 98, 5]).payload == "C"
    assert t.remove(a) == "A"
    assert not t.has(a) and t.has(b) and t.has(c)
    assert t.longest_payload_prefix(list(a) + [1]).payload == "B"
    # Removing everything leaves a clean tree.
    t.remove(b)
    t.remove(c)
    assert len(t) == 0
    assert t.longest_payload_prefix(list(a) + [1]) is None


def test_radix_lcp_candidates():
    t = RadixTree()
    base = tuple(range(100, 120))
    t.insert(base + (1, 2, 3), "k")
    # A different continuation shares the 20-token base (the dense
    # store's derived-entry shape).
    assert t.lcp_candidates(base + (7, 8, 9), min_len=4) == [len(base)]
    # Below min_len: no candidate.
    assert t.lcp_candidates((100, 101, 55), min_len=4) == []
    # Already-stored prefixes are filtered.
    t.insert(base, "p")
    assert t.lcp_candidates(base + (7, 8, 9), min_len=4) == []


def test_radix_deep_chain_is_compressed():
    t = RadixTree()
    long = tuple(range(5, 1005))
    t.insert(long, "L")
    node = t.longest_payload_prefix(list(long) + [1])
    assert node.payload == "L"
    # Path compression: a single entry must not create a per-token chain.
    depth = 0
    while node is not None:
        depth += 1
        node = node.parent
    assert depth <= 3


# --------------------------------------------------------------------- #
# Host tier
# --------------------------------------------------------------------- #

def _panel(seed, tokens=8, rows=None):
    rng = np.random.RandomState(seed)
    rows = rows or tokens
    return (
        jnp.asarray(rng.randn(2, 2, rows, 4).astype(np.float32)),
        jnp.asarray(rng.randn(2, 2, rows, 4).astype(np.float32)),
    )


def test_host_tier_spill_restore_roundtrip():
    tier = HostTier(1 << 20)
    key = tuple(range(40, 56))
    ks, vs = _panel(0, 16)
    assert tier.put(key, (ks, vs), tokens=16, rows=16, kind="dense")
    entry = tier.match(list(key) + [1, 2])
    assert entry is not None and entry.key == key
    hk, hv = entry.copy.wait()
    np.testing.assert_array_equal(hk, np.asarray(ks))
    np.testing.assert_array_equal(hv, np.asarray(vs))
    # Exact-length query is not a proper prefix.
    assert tier.match(list(key)) is None
    assert tier.take(key) is entry and len(tier) == 0


def test_host_tier_budget_eviction_and_policy():
    ks, vs = _panel(1, 16)
    per_entry = np.asarray(ks).nbytes + np.asarray(vs).nbytes
    # The discriminating shape: a is nearly all padding (1 true token in
    # 16 rows) but touched most recently; b is dense and older. Plain
    # LRU protects a; the cost score (recency x FLOPs-saved-per-byte)
    # lets the dense entry outlive the padded one.
    a, b, c = (tuple(range(s, s + 16)) for s in (10, 40, 70))

    def fill(policy):
        tier = HostTier(2 * per_entry, policy=policy)
        tier.put(a, (ks, vs), tokens=1, rows=16, kind="dense")
        tier.put(b, (ks, vs), tokens=16, rows=16, kind="dense")
        assert tier.match(list(a) + [1, 2]) is not None  # touch a
        tier.put(c, (ks, vs), tokens=16, rows=16, kind="dense")
        return tier

    before = global_metrics.get("engine.kvcache.evictions")
    cost = fill("cost")
    assert global_metrics.get("engine.kvcache.evictions") == before + 1
    assert cost.get(b) is not None and cost.get(a) is None

    lru = fill("lru")
    assert lru.get(a) is not None and lru.get(b) is None


def test_host_tier_session_pins_lineage():
    ks, vs = _panel(2, 16)
    per_entry = np.asarray(ks).nbytes + np.asarray(vs).nbytes
    tier = HostTier(2 * per_entry, policy="lru")
    a, b, c = (tuple(range(s, s + 16)) for s in (10, 40, 70))
    tier.put(a, (ks, vs), tokens=16, rows=16, kind="dense")
    tier.note_session("sess-1", list(a) + [1, 2, 3])  # a is on the lineage
    tier.put(b, (ks, vs), tokens=16, rows=16, kind="dense")
    tier.put(c, (ks, vs), tokens=16, rows=16, kind="dense")
    # LRU would evict a; the session pin redirects eviction to b.
    assert tier.get(a) is not None and tier.get(b) is None


def test_host_tier_extension_blocks_contiguity():
    tier = HostTier(1 << 22)
    P = 4
    ids = list(range(30, 60))
    ks, vs = _panel(3, P)
    # Blocks 1 and 3 spilled, block 2 missing: an extension from block 1
    # must stop at the gap.
    tier.put(tuple(ids[: 2 * P]), (ks, vs), tokens=P, rows=P, kind="page")
    tier.put(tuple(ids[: 4 * P]), (ks, vs), tokens=P, rows=P, kind="page")
    ents = tier.extension_blocks(ids, 1, P, 16)
    assert [len(e.key) for e in ents] == [2 * P]
    # With block 2 present the run extends to block 3.
    tier.put(tuple(ids[: 3 * P]), (ks, vs), tokens=P, rows=P, kind="page")
    ents = tier.extension_blocks(ids, 1, P, 16)
    assert [len(e.key) for e in ents] == [2 * P, 3 * P, 4 * P]


def test_prefix_store_single_victim_eviction_and_spill_hook():
    from pilottai_tpu.engine.prefix_cache import PrefixStore

    evicted = []
    s = PrefixStore(capacity=2, min_len=4, max_len=64,
                    on_evict=evicted.append)
    a = tuple(range(10, 30))
    b = tuple(range(40, 56))
    s.store(a, "ka", "va", 32)
    s.store(b, "kb", "vb", 16)
    s.match(list(a) + [1])  # touch a
    s.store(tuple(range(70, 90)), "kc", "vc", 32)
    assert [e.ids for e in evicted] == [b]
    assert s.has(a) and not s.has(b) and len(s) == 2


# --------------------------------------------------------------------- #
# Engine parity: tier on/off, dense/paged x speculation on/off
# --------------------------------------------------------------------- #

# Three lineages with multi-turn resumes; staggered budgets finish slots
# mid-chunk. Submitted sequentially so eviction pressure (hot capacity
# 1-2 entries / 2 pinned pages) forces spill -> restore between turns.
_S1 = [(i % 90) + 5 for i in range(70)]
_S2 = [(i % 70) + 11 for i in range(70)]
_S3 = [(i % 50) + 23 for i in range(70)]
SEQ = (
    (_S1, 6), (_S2, 8), (_S1 + [7, 9, 11], 6), (_S3, 4),
    (_S2 + [17, 18, 19], 8), (_S1 + [7, 9, 11, 13, 15], 5),
)


def _run_seq(*, prefix_cache, host_mb, paged, speculate, page_cap=None,
             session=True):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kwargs = dict(
        n_slots=2, max_seq_len=256, cache_dtype=jnp.float32, chunk_size=4,
        speculate=speculate, prefix_cache=prefix_cache,
        kvcache_host_mb=host_mb, use_pallas=False,
    )
    if paged:
        kwargs.update(paged=True, page_size=16)
    b = ContinuousBatcher(cfg, params, **kwargs)
    if page_cap is not None and b.page_index is not None:
        b.page_index.capacity = page_cap
    b.start()
    try:
        outs = []
        for i, (prompt, mnt) in enumerate(SEQ):
            req = GenRequest(
                prompt_ids=list(prompt), max_new_tokens=mnt,
                session_id=f"sess-{i % 3}" if session else None,
            )
            outs.append(b.submit(req).result(timeout=600))
        return outs
    finally:
        b.stop()


@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 2), (True, 0), (True, 2)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
def test_tier_on_off_greedy_parity(paged, speculate):
    """The acceptance bar: greedy output byte-identical with the tier
    enabled (tiny hot capacity -> spills and restores actually happen)
    vs disabled entirely."""
    cold = _run_seq(prefix_cache=0, host_mb=0, paged=paged,
                    speculate=speculate, session=False)
    before = _kv_counters()
    warm = _run_seq(prefix_cache=1 if not paged else 4, host_mb=64,
                    paged=paged, speculate=speculate,
                    page_cap=2 if paged else None)
    delta = _kv_delta(before)
    assert warm == cold, (
        f"KV cache tier changed greedy output (paged={paged}, "
        f"speculate={speculate})"
    )
    assert delta["spills"] >= 1, "eviction never spilled — tier untested"
    assert delta["restores"] >= 1, "resume never restored — tier untested"
    assert all(len(o) >= 1 for o in cold)  # non-vacuous


# --------------------------------------------------------------------- #
# Resume without re-prefill (the prefill-token counter is the pin)
# --------------------------------------------------------------------- #

def _resume_engine(paged):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kwargs = dict(
        n_slots=2, max_seq_len=256, cache_dtype=jnp.float32, chunk_size=4,
        prefix_cache=1 if not paged else 4, kvcache_host_mb=64,
        use_pallas=False,
    )
    if paged:
        kwargs.update(paged=True, page_size=16)
    b = ContinuousBatcher(cfg, params, **kwargs)
    if paged and b.page_index is not None:
        b.page_index.capacity = 2
    return b


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_spill_evict_resume_skips_reprefill(paged):
    """Session turn 1 caches; unrelated traffic evicts (spill); the
    resume must restore from host RAM and prefill ONLY the new tail —
    pinned by the engine's prefilled-token counter
    (``engine.prefill_tokens``, fed from the admission's true AI_LEN
    rows), not just by the hit counters. 80-token bases clear the dense
    store's 64-token entry floor (entries cache the prompt minus its
    last token)."""
    base = [(i % 90) + 5 for i in range(80)]
    other = [(i % 70) + 11 for i in range(80)]
    resume = base + [7, 9, 11, 13]
    b = _resume_engine(paged)
    b.start()
    try:
        # Submit serially so eviction ordering is deterministic.
        r1 = GenRequest(prompt_ids=list(base), max_new_tokens=6,
                        session_id="s-res")
        b.submit(r1).result(timeout=600)
        r2 = GenRequest(prompt_ids=list(other), max_new_tokens=6)
        b.submit(r2).result(timeout=600)
        if paged:
            # The tiny capacity existed to force the eviction; lift it
            # before the resume so the restored chain isn't immediately
            # re-evicted by its own registration (the production
            # quarter-pool default comfortably holds one chain).
            b.page_index.capacity = 16
        before = _kv_counters()
        pf_before = global_metrics.get("engine.prefill_tokens")
        r3 = GenRequest(prompt_ids=list(resume), max_new_tokens=6,
                        session_id="s-res")
        out = b.submit(r3).result(timeout=600)
        delta = _kv_delta(before)
        prefilled = global_metrics.get("engine.prefill_tokens") - pf_before
    finally:
        b.stop()
    assert delta["restores"] >= 1 and delta["host_hits"] >= 1
    assert delta["prefill_tokens_saved"] > 0
    # The pin: the resume prefilled strictly less than half its prompt
    # (dense restores all but the last token; paged all full blocks).
    assert 0 < prefilled < len(resume) // 2, (
        f"resume re-prefilled {prefilled} of {len(resume)} tokens"
    )
    assert len(out) >= 1


# --------------------------------------------------------------------- #
# Chaos: restores vs the PR 8 fault domain
# --------------------------------------------------------------------- #

def test_restore_in_flight_unwinds_across_rebuild():
    """A staged (not yet applied) restore whose pool is rebuilt must
    unwind cleanly: the stale record is dropped, nothing scatters into
    the fresh pool, and the consumed host entries RETURN to the cold
    tier so the recovered re-admission can restore them again — the
    host tier is rebuild-proof by construction."""
    b = _resume_engine(paged=True)  # not started: device thread is ours
    P = b.page_size
    ids = list(range(40, 40 + 3 * P + 2))
    # Seed the cold tier with the first two blocks directly.
    L, K, H = b.cfg.n_layers, b.cfg.n_kv_heads, b.cfg.head_dim
    for blk in range(2):
        panel = (
            jnp.ones((L, K, P, H), jnp.float32) * (blk + 1),
            jnp.ones((L, K, P, H), jnp.float32) * (blk + 101),
        )
        assert b.kvcache.host.put(
            tuple(ids[: (blk + 1) * P]), panel,
            tokens=P, rows=P, kind="page",
        )
    with b._lock:
        req = GenRequest(prompt_ids=ids, max_new_tokens=4)
        node = b._prefix_hit(req)
    assert node is not None and node.depth == 2
    assert len(b._pending_restores) == 1
    assert len(b.kvcache.host) == 0      # consumed by the restore
    free_before = b.alloc.free_pages
    # PR 8 recovery path: the pool is rebuilt while the restore is
    # still pending.
    b._rebuild_device_state(reason="test_mid_restore")
    b._apply_restores()
    assert b._pending_restores == []
    assert len(b.kvcache.host) == 2, "host entries lost in the unwind"
    assert len(b.page_index) == 0        # live index died with the pool
    # Fresh allocator: nothing leaked from the old epoch.
    assert b.alloc.free_pages >= free_before
    # And the re-admission path can restore again against the new pool.
    with b._lock:
        node2 = b._prefix_hit(GenRequest(prompt_ids=ids, max_new_tokens=4))
    assert node2 is not None and node2.depth == 2
    assert len(b._pending_restores) == 1


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_resume_recovers_from_prefill_fault_mid_restore(paged):
    """engine.rebuild-style chaos (ISSUE 10 satellite): the admission
    dispatch CARRYING a host restore fails with an injected device
    fault. PR 8 semantics must hold — the request re-admits (bounded
    strikes) and completes with output byte-identical to an uninjected
    engine's."""
    base = [(i % 90) + 5 for i in range(80)]
    other = [(i % 70) + 11 for i in range(80)]
    resume = base + [7, 9, 11]

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    want = None
    for inject in (False, True):
        b = _resume_engine(paged)
        b.start()
        try:
            b.submit(GenRequest(prompt_ids=list(base), max_new_tokens=6,
                                session_id="s-c")).result(timeout=600)
            b.submit(GenRequest(prompt_ids=list(other),
                                max_new_tokens=6)).result(timeout=600)
            before = _kv_counters()
            rec_before = global_metrics.get("engine.recovery_requeued")
            if inject:
                global_injector.arm(
                    "engine.prefill",
                    RuntimeError("injected fault mid-restore"), times=1,
                )
            try:
                out = b.submit(GenRequest(
                    prompt_ids=list(resume), max_new_tokens=6,
                    session_id="s-c",
                )).result(timeout=600)
            finally:
                global_injector.disarm("engine.prefill")
            delta = _kv_delta(before)
        finally:
            b.stop()
        assert delta["restores"] >= 1, "scenario never exercised a restore"
        if not inject:
            want = out
        else:
            assert global_injector.fired("engine.prefill") >= 1
            assert (
                global_metrics.get("engine.recovery_requeued") > rec_before
            ), "fault did not route through PR 8 recovery"
            assert out == want, "recovery after mid-restore fault changed output"


# --------------------------------------------------------------------- #
# Session threading (HTTP edge -> params -> engine)
# --------------------------------------------------------------------- #

def test_server_session_id_parsing():
    from pilottai_tpu.server import APIServer, _HttpError

    sid = APIServer._session_id
    assert sid({"session_id": "abc-123"}, {}) == "abc-123"
    assert sid({}, {"x-session-id": "s.9"}) == "s.9"
    assert sid({"session_id": "body"}, {"x-session-id": "hdr"}) == "body"
    assert sid({}, {}) is None
    with pytest.raises(_HttpError):
        sid({"session_id": "bad session!"}, {})
    with pytest.raises(_HttpError):
        sid({"session_id": "x" * 65}, {})


def test_handler_threads_session_id_into_params():
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    h = LLMHandler(LLMConfig(provider="mock"))
    _, _, p = h._normalize(
        ["hi"], None, None, None, session_id="sess-42"
    )
    assert p.session_id == "sess-42"
    # Explicit params win over the caller-level default.
    explicit = GenerationParams(session_id="explicit")
    _, _, p2 = h._normalize(["hi"], None, explicit, None,
                            session_id="sess-42")
    assert p2.session_id == "explicit"
