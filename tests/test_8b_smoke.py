"""North-star model smoke: llama3-8b compiles sharded on a multi-chip mesh.

BASELINE.md names Llama-3-8B on v5e-8 as the target workload; one real
chip can't hold 16 GB of bf16 weights, so this proves the 8B path is
real the way AOT tooling does: abstract-shape parameters carrying the
production NamedShardings, lowered and compiled against the virtual
8-device mesh. No weight memory is ever allocated (VERDICT r1 #9: "the
north-star model must stop being hypothetical").
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pilottai_tpu.models.common import init_params, param_logical_axes
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill
from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.parallel.sharding import named_sharding


def _abstract_sharded_params(cfg, mesh):
    abstract = jax.eval_shape(lambda k: init_params(cfg, k), jax.random.PRNGKey(0))
    axes = param_logical_axes(cfg)
    shardings = jax.tree.map(
        lambda ax: NamedSharding(mesh, P()) if ax is None
        else named_sharding(mesh, ax),
        axes, is_leaf=lambda x: x is None or isinstance(x, tuple),
    )
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract, shardings,
    )


@pytest.mark.parametrize("model,mesh_cfg", [
    ("llama3-8b", MeshConfig(data=1, fsdp=2, model=4, seq=1)),
    ("llama3-8b", MeshConfig(data=2, fsdp=1, model=4, seq=1)),
    ("gemma-2b", MeshConfig(data=1, fsdp=4, model=2, seq=1)),
])
def test_flagship_model_compiles_sharded(model, mesh_cfg):
    cfg = get_model_config(model)
    if model == "llama3-8b":
        assert cfg.param_count() > 7_000_000_000
    mesh = create_mesh(mesh_cfg)
    ap = _abstract_sharded_params(cfg, mesh)

    B, T = 4, 256
    compiled = (
        jax.jit(forward_prefill.__wrapped__, static_argnums=(1,))
        .lower(
            ap, cfg,
            jax.ShapeDtypeStruct((B, T), jnp.int32),
            jax.ShapeDtypeStruct((B, T), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        )
        .compile()
    )
    # The compiled executable sees the full sharded graph: per-device
    # parameter shapes must actually be partitioned, not replicated.
    # cost_analysis() returns one dict per device-program on some jax
    # versions and a bare dict on others.
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = ca.get("flops", 0.0)
    assert flops > 0
    param_shardings = compiled.input_shardings[0][0]
    partitioned = 0
    for leaf_sharding, leaf in zip(
        jax.tree.leaves(param_shardings), jax.tree.leaves(ap)
    ):
        if leaf_sharding.shard_shape(leaf.shape) != leaf.shape:
            partitioned += 1
    assert partitioned > 0, "every parameter came out replicated"
