"""Fused multi-step decode (engine/decode.py) correctness.

The production serving path decodes N tokens per dispatch with on-device
sampling and EOS/budget tracking; these tests pin it to the dense
single-step reference (models/transformer.py:forward_decode) and check
the device-side termination semantics the batcher relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.decode import (
    DecodeState,
    admit_decode,
    decode_chunk,
    release_decode,
    sample_prefill_tokens,
)
from pilottai_tpu.engine.sampling import SamplingState, admit_sampling, sample_core
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_decode, forward_prefill
from pilottai_tpu.ops.kvcache import KVCache, write_prompts


def _admit(cfg, params, temps, budgets, eos=-1, seed0=10):
    """Prefill two prompts into slots 0 and 2 of a 4-slot cache."""
    B, S, A, T = 4, 128, 4, 64
    rng = np.random.default_rng(0)
    lens = np.array([17, 33, 0, 0], np.int32)
    tokens = np.zeros((A, T), np.int32)
    for i in range(2):
        tokens[i, : lens[i]] = rng.integers(2, cfg.vocab_size, lens[i])
    slots = jnp.asarray([0, 2, B, B], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (A, T))

    cache = KVCache.create(
        cfg.n_layers, B, S, cfg.n_kv_heads, cfg.head_dim, dtype=jnp.float32
    )
    sampling = SamplingState.create(B)
    dstate = DecodeState.create(B)
    logits, ks, vs = forward_prefill(
        params, cfg, jnp.asarray(tokens), positions, jnp.asarray(lens)
    )
    cache = write_prompts(cache, slots, ks, vs, jnp.asarray(lens))
    sampling = admit_sampling(
        sampling, slots, jnp.full((A,), float(temps)),
        jnp.zeros(A, jnp.int32), jnp.ones(A),
        jnp.arange(seed0, seed0 + A, dtype=jnp.int32),
        jnp.full((A,), eos, jnp.int32),
        jnp.zeros((A,), bool),
    )
    first, sampling = sample_prefill_tokens(
        logits, jnp.asarray(lens), slots, sampling
    )
    dstate = admit_decode(
        dstate, slots, first, jnp.asarray(budgets, jnp.int32),
        jnp.asarray(lens > 0),
    )
    return cache, dstate, sampling


@pytest.mark.parametrize("cfg_name", ["llama-tiny", "gemma-tiny"])
def test_chunked_decode_matches_stepwise(cfg_name):
    """12 tokens via 3 fused chunks == 12 single steps, with temperature
    sampling (full-distribution sensitive) and shared PRNG evolution."""
    cfg = get_model_config(cfg_name)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    # Random-init logits are peaked; high temperature flattens them so the
    # sampled ids depend on the whole distribution, not just the argmax.
    cache, dstate, sampling = _admit(cfg, params, temps=30.0, budgets=[20, 20, 0, 0])

    ref_cache = KVCache(
        layers=tuple((k.copy(), v.copy()) for k, v in cache.layers),
        lengths=cache.lengths.copy(),
    )
    ref_sampling = SamplingState(*[a.copy() for a in sampling])
    cur = dstate.tokens.copy()
    active = jnp.asarray([True, False, True, False])
    ref = {0: [], 2: []}
    for _ in range(12):
        lg, ref_cache = forward_decode(params, cfg, cur, ref_cache, active)
        nxt, ref_sampling = sample_core(lg, ref_sampling)
        cur = jnp.where(active, nxt, cur)
        ref[0].append(int(nxt[0]))
        ref[2].append(int(nxt[2]))

    got = {0: [], 2: []}
    for _ in range(3):
        toks, valid, cache, dstate, sampling = decode_chunk(
            params, cfg, cache, dstate, sampling, 4, use_pallas=False
        )
        toks, valid = np.asarray(toks), np.asarray(valid)
        for b in (0, 2):
            got[b] += [int(toks[i, b]) for i in range(4) if valid[i, b]]

    assert got[0] == ref[0] and got[2] == ref[2]
    assert len(set(got[0])) > 2, "degenerate sequence makes this test vacuous"
    # Cache lengths advanced by exactly the generated tokens.
    np.testing.assert_array_equal(
        np.asarray(cache.lengths), [17 + 12, 0, 33 + 12, 0]
    )


def test_device_budget_stops_generation():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    # Slot 0: 3 more tokens allowed; slot 2: 20.
    cache, dstate, sampling = _admit(cfg, params, temps=0.0, budgets=[3, 20, 0, 0])
    toks, valid, cache, dstate, sampling = decode_chunk(
        params, cfg, cache, dstate, sampling, 8, use_pallas=False
    )
    valid = np.asarray(valid)
    assert valid[:, 0].sum() == 3 and bool(np.asarray(dstate.done)[0])
    assert valid[:, 2].sum() == 8 and not bool(np.asarray(dstate.done)[2])
    np.testing.assert_array_equal(np.asarray(cache.lengths), [20, 0, 41, 0])


def test_device_eos_stops_generation():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache, dstate, sampling = _admit(cfg, params, temps=0.0, budgets=[20, 20, 0, 0])
    # Find what greedy emits first, then rerun with that id as EOS: the
    # slot must stop after emitting it.
    toks, valid, *_ = decode_chunk(
        params, cfg, cache, dstate, sampling, 4, use_pallas=False
    )
    eos = int(np.asarray(toks)[0, 0])
    cache, dstate, sampling = _admit(cfg, params, temps=0.0,
                                     budgets=[20, 20, 0, 0], eos=eos)
    toks, valid, cache, dstate, sampling = decode_chunk(
        params, cfg, cache, dstate, sampling, 8, use_pallas=False
    )
    valid = np.asarray(valid)
    assert valid[:, 0].sum() == 1, "slot 0 should stop right after EOS"
    assert bool(np.asarray(dstate.done)[0])


def test_release_decode_stops_slot():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache, dstate, sampling = _admit(cfg, params, temps=0.0, budgets=[20, 20, 0, 0])
    dstate = release_decode(dstate, jnp.asarray([0, 4, 4, 4], jnp.int32))
    toks, valid, cache, dstate, sampling = decode_chunk(
        params, cfg, cache, dstate, sampling, 4, use_pallas=False
    )
    valid = np.asarray(valid)
    assert valid[:, 0].sum() == 0 and valid[:, 2].sum() == 4


def test_pallas_decode_attention_interpret_matches_dense():
    """The Pallas prefix kernel (interpret mode on CPU) must agree with the
    dense stats fallback — same (acc, m, l) contract, same masking."""
    from pilottai_tpu.engine.decode import _prefix_stats_dense
    from pilottai_tpu.ops.pallas.decode_attention import decode_attention

    rng = np.random.default_rng(3)
    for (B, N, K, S, H, softcap, window) in [
        (3, 8, 2, 128, 64, 0.0, 0),
        (2, 8, 8, 64, 64, 30.0, 0),
        (2, 16, 4, 128, 64, 0.0, 48),
    ]:
        G = N // K
        q = jnp.asarray(rng.standard_normal((B, N, H)), jnp.float32)
        kc = jnp.asarray(rng.standard_normal((B, K, S, H)), jnp.float32)
        vc = jnp.asarray(rng.standard_normal((B, K, S, H)), jnp.float32)
        last = jnp.asarray(rng.integers(-1, S - 1, (B,)), jnp.int32)
        qpos = last + 5
        acc, m, l = decode_attention(
            q, kc, vc, last, q_positions=qpos, softcap=softcap, window=window,
            return_stats=True, interpret=True,
        )
        acc_r, m_r, l_r = _prefix_stats_dense(
            q.reshape(B, K, G, H), kc, vc, last, qpos,
            H ** -0.5, softcap, window,
        )
        np.testing.assert_allclose(np.asarray(m), np.asarray(m_r), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(l), np.asarray(l_r), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(acc), np.asarray(acc_r), rtol=1e-3, atol=1e-3
        )


def test_prefix_bound_parity():
    """A chunk reading only the first ``bound`` cache columns must produce
    bit-identical tokens when every live slot's length fits the bound —
    the contract the batcher's _decode_bucket relies on (the cache is 128
    wide here, prompts are 17/33 long, bound 64 covers both)."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache, dstate, sampling = _admit(cfg, params, temps=30.0, budgets=[20, 20, 0, 0])
    ref_cache = KVCache(
        layers=tuple((k.copy(), v.copy()) for k, v in cache.layers),
        lengths=cache.lengths.copy(),
    )
    ref_sampling = SamplingState(*[a.copy() for a in sampling])
    ref_dstate = DecodeState(*[a.copy() for a in dstate])

    t_full, v_full, cache, dstate, _ = decode_chunk(
        params, cfg, cache, dstate, sampling, 8, use_pallas=False
    )
    t_b, v_b, bcache, bdstate, _ = decode_chunk(
        params, cfg, ref_cache, ref_dstate, ref_sampling, 8,
        use_pallas=False, prefix_bound=64,
    )
    np.testing.assert_array_equal(np.asarray(t_full), np.asarray(t_b))
    np.testing.assert_array_equal(np.asarray(v_full), np.asarray(v_b))
    np.testing.assert_array_equal(
        np.asarray(cache.lengths), np.asarray(bcache.lengths)
    )
    # Written cache contents agree wherever tokens landed.
    for (k_f, v_f), (k_p, v_p) in zip(cache.layers, bcache.layers):
        np.testing.assert_allclose(
            np.asarray(k_f), np.asarray(k_p), atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(v_f), np.asarray(v_p), atol=1e-6
        )
