"""Degraded-mesh planning unit tests (ISSUE 16 tentpole, fast lane).

Pure ladder/classifier arithmetic on the virtual 8-device CPU mesh
(tests/conftest.py) — no engine boot. The engine-integrated shard-loss
acceptance lives in tests/test_degraded_mesh.py.
"""

import threading
import time

import pytest

from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.parallel.meshplan import (
    MeshLadderExhausted,
    MeshPlanLadder,
    ShardLossError,
    classify_device_error,
    default_ladder,
    plan_label,
)


def _mesh(shape):
    return create_mesh(MeshConfig.from_dict(shape))


# --------------------------------------------------------------------- #
# Ladder construction
# --------------------------------------------------------------------- #

def test_default_ladder_sheds_replica_axes_before_model():
    """{'model':4,'data':2} halves data first (capacity), model last
    (layout) — the documented shed order."""
    rungs = default_ladder({"model": 4, "data": 2})
    assert [(r["model"], r["data"]) for r in rungs] == [
        (4, 2), (4, 1), (2, 1), (1, 1),
    ]


def test_default_ladder_single_chip_is_identity():
    assert default_ladder({}) == [
        {"data": 1, "fsdp": 1, "model": 1, "seq": 1}
    ]


def test_plan_label_drops_unit_axes():
    assert plan_label({"model": 2, "data": 2}) == "data2xmodel2"
    assert plan_label({"model": 2, "data": 1}) == "model2"
    assert plan_label({"model": 1}) == "single"


def test_boot_plan_always_rung_zero():
    """An explicit ladder that omits the boot plan gets it inserted at
    rung 0 — otherwise a fresh engine would report degraded at boot."""
    ladder = MeshPlanLadder(
        _mesh({"model": 2, "data": 2}), rungs=[{"model": 2}]
    )
    assert ladder.rung == 0
    assert plan_label(ladder.plan()) == "data2xmodel2"
    assert [plan_label(p) for p in ladder.plans()] == [
        "data2xmodel2", "model2",
    ]


def test_oversized_rung_rejected():
    with pytest.raises(ValueError, match="needs 16 devices"):
        MeshPlanLadder(
            _mesh({"model": 2, "data": 2}),
            rungs=[{"model": 2, "data": 2}, {"model": 4, "data": 4}],
        )


# --------------------------------------------------------------------- #
# Error classification
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("exc,want", [
    (ShardLossError(3), 3),
    (RuntimeError("lost shard: device 2 failed"), 2),
    (RuntimeError("device 5 unavailable during collective"), 5),
    (RuntimeError("Lost device 1 (ICI link down)"), 1),
    (RuntimeError("DATA_LOSS: device 7 returned garbage"), 7),
    # Narrow on purpose: naming a device is not asserting its failure.
    (RuntimeError("XLA compile error on device 0"), None),
    (RuntimeError("out of memory"), None),
    (ValueError("device 3"), None),
])
def test_classify_device_error(exc, want):
    assert classify_device_error(exc) == want


# --------------------------------------------------------------------- #
# Loss bookkeeping + replan
# --------------------------------------------------------------------- #

def test_replan_walks_ladder_to_first_fitting_rung():
    ladder = MeshPlanLadder(_mesh({"model": 2, "data": 2}))
    assert ladder.viable()
    ladder.mark_lost(1)
    assert ladder.lost() == [1]
    assert len(ladder.surviving()) == 3
    assert ladder.viable()
    mesh = ladder.replan()
    # 3 survivors can't fit the 4-device boot rung; first fit is model2.
    assert ladder.rung == 1
    assert plan_label(ladder.plan()) == "model2"
    assert mesh.devices.size == 2
    snap = ladder.snapshot()
    assert snap["rung"] == 1 and snap["lost"] == [1]
    assert not snap["exhausted"]


def test_replan_is_monotonic_down_the_ladder():
    """Rungs never climb back up: after degrading to model2, a further
    loss continues the walk from the active rung."""
    ladder = MeshPlanLadder(_mesh({"model": 2, "data": 2}))
    ladder.mark_lost(0)
    ladder.replan()
    assert ladder.rung == 1
    ladder.mark_lost(2)
    ladder.mark_lost(3)
    ladder.replan()
    assert plan_label(ladder.plan()) == "single"
    assert ladder.mesh.devices.size == 1


def test_ladder_exhausted_raises_and_sets_flag():
    ladder = MeshPlanLadder(
        _mesh({"model": 2, "data": 2}), rungs=[{"model": 2, "data": 2}]
    )
    ladder.mark_lost(2)
    assert not ladder.viable()
    with pytest.raises(MeshLadderExhausted, match="lost=\\[2\\]"):
        ladder.replan()
    assert ladder.exhausted


# --------------------------------------------------------------------- #
# Per-shard heartbeats
# --------------------------------------------------------------------- #

def test_frozen_shard_goes_stale_while_siblings_beat():
    ladder = MeshPlanLadder(_mesh({"model": 2, "data": 2}))
    ladder.freeze(2)
    time.sleep(0.02)
    ladder.beat_all()
    assert ladder.stale(0.01) == [2]
    # Marking it lost removes it from the stale set (it's accounted).
    ladder.mark_lost(2)
    assert ladder.stale(0.01) == []


def test_beat_all_is_safe_under_concurrent_freeze():
    """beat_all is lock-free by contract (fold path); hammer it against
    freeze/mark_lost from another thread."""
    ladder = MeshPlanLadder(_mesh({"model": 2, "data": 2}))
    stop = threading.Event()

    def beater():
        while not stop.is_set():
            ladder.beat_all()

    t = threading.Thread(target=beater)
    t.start()
    try:
        for i in range(4):
            ladder.freeze(i % 4)
            ladder.mark_lost(i % 4)
    finally:
        stop.set()
        t.join(timeout=5)
    assert not t.is_alive()
    assert ladder.lost() == [0, 1, 2, 3]
