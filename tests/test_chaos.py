"""Chaos suite: fault-injection driven tests of the reliability layer.

Every test here provokes a failure path through the *named injection
registry* (pilottai_tpu/reliability/inject.py) — no monkeypatching of
engine internals — and asserts the system stays bounded: deadlines bound
wall time end-to-end, overload sheds instead of queueing unboundedly,
the breaker fast-fails and recovers, and an injected device failure
fails exactly the in-flight work while queued requests survive.

The whole module carries the ``chaos`` marker (the CI chaos job runs
``pytest -m chaos``); soak variants are additionally ``slow`` so they
stay out of the tier-1 lane.
"""

import asyncio
import json
import time

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    ReliabilityConfig,
    ServeConfig,
)
from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.reliability import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    EngineOverloaded,
    FaultInjector,
    global_injector,
    inject,
)
from pilottai_tpu.utils.metrics import global_metrics

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_injector():
    global_injector.reset()
    yield
    global_injector.reset()


def _tiny_batcher(max_seq=64, n_slots=2, **kw):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return ContinuousBatcher(
        cfg, params, n_slots=n_slots, max_seq_len=max_seq,
        cache_dtype=jnp.float32, **kw,
    )


# ----------------------------- injector -------------------------------- #

def test_injector_noop_arm_times_and_scope():
    # Unarmed = production fast path: returns None, no record.
    assert global_injector.fire("engine.step") is None
    assert global_injector.fired("engine.step") == 0

    global_injector.arm("x.point", value=42, times=2)
    assert global_injector.fire("x.point") == 42
    assert global_injector.armed("x.point")
    assert global_injector.fire("x.point") == 42
    # times exhausted -> auto-disarmed, count survives.
    assert not global_injector.armed("x.point")
    assert global_injector.fire("x.point") is None
    assert global_injector.fired("x.point") == 2

    with inject("y.point", RuntimeError, times=None):
        with pytest.raises(RuntimeError, match="injected fault at 'y.point'"):
            global_injector.fire("y.point")
    # Context exit disarms even with times=None.
    assert global_injector.fire("y.point") is None


def test_injector_probability_is_seeded_and_partial():
    def run(seed):
        reg = FaultInjector(seed=seed)
        reg.arm("p", value=1, times=None, probability=0.5)
        return [reg.fire("p") for _ in range(200)]

    fires = sum(v == 1 for v in run(7))
    assert 40 < fires < 160  # partial, not all-or-nothing
    assert run(7) == run(7)  # reproducible chaos soaks


def test_injector_delay_blocks_then_returns():
    global_injector.arm("d", delay=0.05, value="v")
    t0 = time.perf_counter()
    assert global_injector.fire("d") == "v"
    assert time.perf_counter() - t0 >= 0.05


# ----------------------------- breaker --------------------------------- #

def test_breaker_opens_after_threshold_and_fast_fails():
    br = CircuitBreaker(failure_threshold=3, recovery_timeout=30.0, name="t1")
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == "closed"
    assert br.allow()
    br.record_failure()  # third consecutive -> open
    assert br.state == "open"
    assert not br.allow()
    assert br.retry_after() > 0
    err = br.open_error()
    assert isinstance(err, CircuitOpenError) and err.retry_after > 0


def test_breaker_half_open_probe_paths():
    br = CircuitBreaker(
        failure_threshold=1, recovery_timeout=0.05, half_open_max=1, name="t2"
    )
    br.record_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.state == "half_open"
    assert br.allow()       # the probe slot
    assert not br.allow()   # only half_open_max probes pass
    br.record_failure()     # probe failed -> re-open, window re-armed
    assert br.state == "open"
    time.sleep(0.06)
    assert br.allow()
    br.record_success()     # probe succeeded -> closed
    assert br.state == "closed" and br.allow()


def test_breaker_released_probe_does_not_wedge_half_open():
    # A probe that ends with NO verdict (cancelled mid-flight) must give
    # its slot back — leaked slots would pin allow() False forever.
    br = CircuitBreaker(
        failure_threshold=1, recovery_timeout=0.05, half_open_max=1, name="t4"
    )
    br.record_failure()
    time.sleep(0.06)
    assert br.allow()        # probe reserved...
    br.release_probe()       # ...but the call was cancelled: release
    assert br.allow()        # the slot is available again
    br.record_success()
    assert br.state == "closed"


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=2, name="t3")
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # never 2 consecutive


# ------------------------- handler reliability -------------------------- #

def _handler(backend, **rel_kw):
    cfg_kw = {
        k: rel_kw.pop(k)
        for k in ("retries", "retry_delay", "timeout")
        if k in rel_kw
    }
    return LLMHandler(
        LLMConfig(
            provider="mock",
            reliability=ReliabilityConfig(**rel_kw),
            **cfg_kw,
        ),
        backend=backend,
    )


def test_backoff_is_exponential_capped_and_jittered():
    h = _handler(
        MockBackend(), retries=0, retry_delay=1.0,
        retry_max_delay=4.0, retry_jitter=False,
    )
    assert [h._backoff_delay(a) for a in range(4)] == [1.0, 2.0, 4.0, 4.0]
    hj = _handler(
        MockBackend(), retries=0, retry_delay=1.0, retry_max_delay=4.0,
    )
    for attempt, base in enumerate([1.0, 2.0, 4.0, 4.0]):
        for _ in range(20):
            d = hj._backoff_delay(attempt)
            assert 0.5 * base <= d <= base


@pytest.mark.asyncio
async def test_handler_breaker_opens_then_recovers_half_open():
    calls = {"n": 0, "healthy": False}

    class Flaky(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            if not calls["healthy"]:
                raise RuntimeError("device gone")
            return await super().generate(messages, tools, params)

    h = _handler(
        Flaky(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=2, breaker_recovery_timeout=0.1,
    )
    for _ in range(2):
        with pytest.raises(RuntimeError):
            await h.apredict("x")
    assert calls["n"] == 2 and h.breaker.state == "open"
    # Open -> fast fail without touching the backend.
    with pytest.raises(CircuitOpenError):
        await h.apredict("x")
    assert calls["n"] == 2
    # Recovery window -> half-open probe -> success closes it.
    calls["healthy"] = True
    await asyncio.sleep(0.12)
    assert await h.apredict("x")
    assert h.breaker.state == "closed" and calls["n"] == 3


@pytest.mark.asyncio
async def test_handler_timeout_injection_feeds_breaker():
    """Breaker open -> fast-fail -> half-open recovery, driven purely by
    the injection registry (acceptance criterion)."""
    backend_calls = {"n": 0}

    class Counting(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            backend_calls["n"] += 1
            return await super().generate(messages, tools, params)

    h = _handler(
        Counting(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=2, breaker_recovery_timeout=0.1,
    )
    global_injector.arm("handler.timeout", asyncio.TimeoutError, times=2)
    for _ in range(2):
        with pytest.raises(RuntimeError, match="failed after 1 attempt"):
            await h.apredict("x")
    assert backend_calls["n"] == 0  # fault fired before the backend
    assert h.breaker.state == "open"
    with pytest.raises(CircuitOpenError):
        await h.apredict("x")
    await asyncio.sleep(0.12)
    assert await h.apredict("x")  # injection exhausted -> probe succeeds
    assert h.breaker.state == "closed"
    assert global_injector.fired("handler.timeout") == 2


@pytest.mark.asyncio
async def test_handler_deadline_preempts_backend_and_backoff():
    calls = {"n": 0}

    class Slow(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            await asyncio.sleep(0.5)
            return await super().generate(messages, tools, params)

    h = _handler(Slow(), retries=3, retry_delay=5.0, breaker_enabled=False)
    # Born expired: no backend call at all.
    with pytest.raises(DeadlineExceeded):
        await h.apredict(
            "x", params=GenerationParams(deadline=time.monotonic() - 1)
        )
    assert calls["n"] == 0
    # Deadline clips the wait: fails in ~0.1s, and the 5s backoff must
    # not be slept through either (the deadline pre-empts the retry).
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceeded):
        await h.apredict(
            "x", params=GenerationParams(deadline=time.monotonic() + 0.1)
        )
    assert time.perf_counter() - t0 < 0.45
    assert calls["n"] == 1


@pytest.mark.asyncio
async def test_handler_overload_is_not_retried_and_not_breaker_failure():
    calls = {"n": 0}

    class Shedding(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            raise EngineOverloaded("queue full")

    h = _handler(
        Shedding(), retries=3, retry_delay=0.0, breaker_failure_threshold=1,
    )
    with pytest.raises(EngineOverloaded):
        await h.apredict("x")
    assert calls["n"] == 1  # no retry: push-back means push-back
    assert h.breaker.state == "closed"  # shed != device failure


@pytest.mark.asyncio
async def test_astream_shed_is_not_a_breaker_failure():
    class SheddingStream(MockBackend):
        async def generate_stream(
            self, messages, tools=None, params=None, info=None
        ):
            raise EngineOverloaded("stream shed")
            yield  # pragma: no cover — makes this an async generator

    h = _handler(SheddingStream(), retries=0, breaker_failure_threshold=1)
    with pytest.raises(EngineOverloaded):
        async for _ in h.astream("x"):
            pass
    assert h.breaker.state == "closed"  # unary-path parity: shed != failure


# --------------------------- batcher chaos ------------------------------ #

def test_deadline_bounds_request_against_slow_engine():
    """Acceptance: a short deadline against a chaos-slowed engine returns
    a structured timeout error and the slot is NOT leaked (n_slots=1 —
    the follow-up request can only complete through the freed slot)."""
    b = _tiny_batcher(n_slots=1)
    b.start()
    try:
        with inject("engine.prefill", delay=0.3, times=None):
            req = GenRequest(
                prompt_ids=[3, 4, 5], max_new_tokens=48,
                deadline=time.monotonic() + 0.1,
            )
            fut = b.submit(req)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=120)
        assert global_injector.fired("engine.prefill") >= 1
        # Slot freed: a fresh request (no deadline) completes through it.
        req2 = GenRequest(prompt_ids=[6, 7], max_new_tokens=4)
        out = b.submit(req2).result(timeout=120)
        assert isinstance(out, list) and len(out) >= 1
        assert b._thread.is_alive()
    finally:
        b.stop()


def test_deadline_expired_in_backlog_rejected_at_admission():
    b = _tiny_batcher(n_slots=1)
    req = GenRequest(
        prompt_ids=[3, 4], max_new_tokens=4,
        deadline=time.monotonic() + 0.05,
    )
    fut = b.submit(req)  # queued while the loop isn't running yet
    time.sleep(0.1)
    before = global_metrics.get("engine.expired")
    b.start()
    try:
        with pytest.raises(DeadlineExceeded, match="before admission"):
            fut.result(timeout=60)
        assert global_metrics.get("engine.expired") >= before + 1
    finally:
        b.stop()


def test_deadline_expired_before_submit_costs_nothing():
    b = _tiny_batcher(n_slots=1)  # never started: submit path only
    req = GenRequest(
        prompt_ids=[3], max_new_tokens=4, deadline=time.monotonic() - 1,
    )
    fut = b.submit(req)
    with pytest.raises(DeadlineExceeded, match="before submit"):
        fut.result(timeout=1)
    assert b.queue_depth() == 0  # no queue entry exists for it


def test_queue_depth_shedding_while_inflight_completes():
    """Acceptance: submits beyond max_queue_depth raise EngineOverloaded
    (the HTTP edge maps it to 429) while already-accepted requests
    complete untouched."""
    b = _tiny_batcher(n_slots=1, max_queue_depth=2)
    futs = [
        b.submit(GenRequest(prompt_ids=[3, 4], max_new_tokens=3))
        for _ in range(2)
    ]
    assert b.saturated()
    with pytest.raises(EngineOverloaded, match="shedding"):
        b.submit(GenRequest(prompt_ids=[5], max_new_tokens=3))
    assert global_metrics.get("engine.shed") >= 1
    b.start()
    try:
        for fut in futs:  # the accepted work still completes
            assert isinstance(fut.result(timeout=120), list)
    finally:
        b.stop()


def test_injected_step_failure_fails_occupied_not_queued():
    """Satellite: chaos-driven regression for the device-failure path —
    _fail_occupied_slots fails the in-flight request with the ORIGINAL
    exception; the queued request survives and completes."""
    b = _tiny_batcher(n_slots=1)
    global_injector.arm(
        "engine.step", RuntimeError("injected device failure"), times=1
    )
    b.start()
    try:
        fut1 = b.submit(GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=32))
        fut2 = b.submit(GenRequest(prompt_ids=[6, 7], max_new_tokens=4))
        with pytest.raises(RuntimeError, match="injected device failure"):
            fut1.result(timeout=120)
        out = fut2.result(timeout=120)  # queued work survived the failure
        assert isinstance(out, list) and len(out) >= 1
        assert b._thread.is_alive() and b._reader.is_alive()
        assert global_injector.fired("engine.step") == 1
    finally:
        b.stop()


@pytest.mark.slow
def test_chaos_soak_probabilistic_step_failures():
    """Soak (chaos lane only): every request resolves — result or the
    injected error — under randomized dispatch failures, and the engine
    stays serviceable afterwards."""
    b = _tiny_batcher(n_slots=2)
    b.start()
    try:
        with inject(
            "engine.step", RuntimeError("soak fault"),
            times=None, probability=0.3,
        ):
            futs = [
                b.submit(GenRequest(
                    prompt_ids=[3 + i, 4, 5], max_new_tokens=8, seed=i,
                ))
                for i in range(12)
            ]
            resolved = 0
            for fut in futs:
                try:
                    assert isinstance(fut.result(timeout=180), list)
                except RuntimeError as exc:
                    assert "soak fault" in str(exc)
                resolved += 1
            assert resolved == 12
        out = b.submit(
            GenRequest(prompt_ids=[9, 9], max_new_tokens=4)
        ).result(timeout=120)
        assert isinstance(out, list)
    finally:
        b.stop()


# ----------------------------- HTTP edge -------------------------------- #

async def _request(port, method, path, body=None, headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n{extra}"
        f"Connection: close\r\n\r\n".encode() + payload
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body_bytes = raw.partition(b"\r\n\r\n")
    status = int(head.split(b"\r\n")[0].split(b" ")[1])
    return status, json.loads(body_bytes) if body_bytes else {}


class _RaisingBackend(MockBackend):
    def __init__(self, exc):
        super().__init__()
        self._exc = exc

    async def generate(self, messages, tools=None, params=None):
        raise self._exc


@pytest.mark.asyncio
async def test_http_shed_is_429_with_structured_error():
    from pilottai_tpu.server import APIServer

    h = _handler(_RaisingBackend(EngineOverloaded("queue depth 64 at limit")))
    server = await APIServer(h).start()
    try:
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
        )
        assert status == 429
        assert data["error"]["type"] == "overloaded_error"
        assert "queue depth" in data["error"]["message"]
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_deadline_is_408_and_breaker_open_is_503():
    from pilottai_tpu.server import APIServer

    class Slow(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            await asyncio.sleep(0.5)
            return await super().generate(messages, tools, params)

    h = _handler(
        Slow(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=1, breaker_recovery_timeout=60.0,
    )
    server = await APIServer(h).start()
    try:
        # Deadline from the x-request-timeout header -> structured 408.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
            headers={"x-request-timeout": "0.05"},
        )
        assert status == 408
        assert data["error"]["type"] == "timeout_error"
        # That deadline blowout opened the breaker (threshold 1):
        # the next request fast-fails 503 with a retry_after hint.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
        )
        assert status == 503
        assert data["error"]["type"] == "overloaded_error"
        assert data["error"]["retry_after"] > 0
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_timeout_field_validation():
    from pilottai_tpu.server import APIServer

    server = await APIServer(_handler(MockBackend())).start()
    try:
        for bad in ("soon", -1, 0, True):
            status, data = await _request(
                server.port, "POST", "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "timeout": bad},
            )
            assert status == 400, bad
        # A generous valid timeout: request completes normally.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}],
             "timeout": 30},
        )
        assert status == 200
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_task_timeout_is_408():
    from pilottai_tpu.server import APIServer

    class HangingServe:
        async def execute_task(self, task, timeout=None):
            await asyncio.wait_for(asyncio.sleep(60), timeout)

    server = await APIServer(
        _handler(MockBackend()), serve=HangingServe()
    ).start()
    try:
        status, data = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "hangs forever", "timeout": 0.1},
        )
        assert status == 408
        assert data["error"]["type"] == "timeout_error"
    finally:
        await server.stop()


# ------------------------ orchestration chaos --------------------------- #

def _worker(**cfg):
    from pilottai_tpu.core.agent import BaseAgent

    return BaseAgent(
        config=AgentConfig(role="worker", **cfg),
        llm=LLMHandler(LLMConfig(provider="mock")),
    )


@pytest.mark.asyncio
async def test_heartbeat_stall_injection_degrades_health():
    from pilottai_tpu.core.status import HealthStatus
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=60.0, max_recovery_attempts=0,
    ))
    ft.register_agent(agent)
    assert (await ft.check_once())[agent.id] == HealthStatus.HEALTHY
    # Inject a 120s stall: the agent LOOKS silent without being wedged.
    global_injector.arm("agent.heartbeat.stall", value=120.0, times=1)
    assert (await ft.check_once())[agent.id] == HealthStatus.UNHEALTHY
    # Injection consumed -> next pass sees the real (fresh) heartbeat.
    assert (await ft.check_once())[agent.id] == HealthStatus.HEALTHY
    await agent.stop()


@pytest.mark.asyncio
async def test_heartbeat_stall_attributed_as_dag_retry_node():
    """An injected ``agent.heartbeat.stall`` that triggers recovery must
    surface in the affected task's DAG as a ``retry`` node carrying the
    observed stall seconds — chaos-induced dead time is attributed, not
    silently swallowed (obs/dag.py)."""
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.obs import global_dag
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos-dag", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=60.0, max_recovery_attempts=1,
        recovery_cooldown=0.0,
    ))
    ft.register_agent(agent)
    task = Task(description="work interrupted by a stalled heartbeat")
    global_dag.start(task.id, trace_id="chaos-dag-stall-1")
    await agent.add_task(task)
    global_injector.arm("agent.heartbeat.stall", value=120.0, times=1)
    await ft.check_once()  # UNHEALTHY -> in-place recovery path
    try:
        d = global_dag.describe(task.id)
        assert d is not None
        retries = [
            n for n in d["nodes"]
            if n["kind"] == "retry" and n["name"] == "agent_recovery"
        ]
        assert retries, [n["name"] for n in d["nodes"]]
        # The injected 120 s stall (minus the loop's own wall) is
        # attributed on the retry node.
        assert retries[0]["attributes"]["stall_s"] >= 60.0
        assert retries[0]["attributes"]["agent_id"] == agent.id[:8]
    finally:
        global_dag.finish(task.id, "cancelled")
        await agent.stop()


@pytest.mark.asyncio
async def test_fault_requeue_adapts_to_orchestrator_signature():
    """The requeue kwargs are filtered per-parameter against the
    orchestrator's signature: a `reason`-only orchestrator must not be
    handed stall_s (TypeError → task lost), a **kwargs one gets the
    full attribution, and a bare legacy one gets the task alone."""
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance

    task = Task(description="requeue me")
    calls = []

    class ReasonOnly:
        def agent_list(self):
            return []

        async def requeue_task(self, task, reason=""):
            calls.append(("reason_only", reason))

    class FullKwargs:
        def agent_list(self):
            return []

        async def requeue_task(self, task, reason="", **attrs):
            calls.append(("full", reason, attrs))

    class Legacy:
        def agent_list(self):
            return []

        async def requeue_task(self, task):
            calls.append(("legacy",))

    for orch in (ReasonOnly(), FullKwargs(), Legacy()):
        ft = FaultTolerance(orch, FaultToleranceConfig())
        await ft._requeue(task, stall_s=12.0)
    assert calls == [
        ("reason_only", "fault_recovery"),
        ("full", "fault_recovery", {"stall_s": 12.0}),
        ("legacy",),
    ]


@pytest.mark.asyncio
async def test_health_gauge_keyed_by_full_id_and_reaped():
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(max_recovery_attempts=0))
    await ft.check_once()
    gauges = global_metrics.snapshot()["gauges"]
    assert f"fault.health.{agent.id}" in gauges  # full id, not id[:8]
    assert f"fault.health.{agent.id[:8]}" not in gauges
    # Agent leaves the pool -> record AND gauge reaped.
    serve.agents.pop(agent.id)
    await ft.check_once()
    gauges = global_metrics.snapshot()["gauges"]
    assert f"fault.health.{agent.id}" not in gauges
    assert agent.id not in ft.health
    await agent.stop()


@pytest.mark.asyncio
async def test_execute_task_timeout_threads_into_task_timeout():
    from pilottai_tpu.serve import Serve

    agent = _worker()
    serve = Serve(
        name="chaos", agents=[agent],
        manager_llm=LLMHandler(LLMConfig(provider="mock")),
        config=ServeConfig(max_concurrent_tasks=2),
    )
    await serve.start()
    try:
        result = await serve.execute_task("trivial thing", timeout=7.5)
        assert result.success
        task = next(
            t for t in serve.all_tasks.values()
            if t.description == "trivial thing"
        )
        assert task.timeout == 7.5  # agents see the caller's budget
    finally:
        await serve.stop()


def test_journal_write_failure_degrades_not_crashes(tmp_path):
    from pilottai_tpu.checkpoint.journal import TaskJournal
    from pilottai_tpu.core.task import Task

    journal = TaskJournal(tmp_path / "j.jsonl")
    before = global_metrics.get("journal.write_failures")
    global_injector.arm("checkpoint.write", OSError("disk full"), times=1)
    journal.record_task(Task(description="survives injected disk failure"))
    assert global_metrics.get("journal.write_failures") == before + 1
    # Disk "recovers": subsequent records land and replay sees them.
    t2 = Task(description="after recovery")
    journal.record_task(t2)
    journal.close()
    assert t2.id in TaskJournal.replay(journal.path)
