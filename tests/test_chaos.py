"""Chaos suite: fault-injection driven tests of the reliability layer.

Every test here provokes a failure path through the *named injection
registry* (pilottai_tpu/reliability/inject.py) — no monkeypatching of
engine internals — and asserts the system stays bounded: deadlines bound
wall time end-to-end, overload sheds instead of queueing unboundedly,
the breaker fast-fails and recovers, and an injected device failure
fails exactly the in-flight work while queued requests survive.

The whole module carries the ``chaos`` marker (the CI chaos job runs
``pytest -m chaos``); soak variants are additionally ``slow`` so they
stay out of the tier-1 lane.
"""

import asyncio
import json
import time

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    ReliabilityConfig,
    ServeConfig,
)
from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.reliability import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    DegradeLadder,
    EngineHealth,
    EngineOverloaded,
    FaultInjector,
    PoisonedOutput,
    Watchdog,
    global_engine_health,
    global_injector,
    inject,
)
from pilottai_tpu.utils.metrics import global_metrics

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_injector():
    global_injector.reset()
    global_engine_health.reset()
    yield
    global_injector.reset()
    global_engine_health.reset()


def _tiny_batcher(max_seq=64, n_slots=2, **kw):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return ContinuousBatcher(
        cfg, params, n_slots=n_slots, max_seq_len=max_seq,
        cache_dtype=jnp.float32, **kw,
    )


# ----------------------------- injector -------------------------------- #

def test_injector_noop_arm_times_and_scope():
    # Unarmed = production fast path: returns None, no record.
    assert global_injector.fire("engine.step") is None
    assert global_injector.fired("engine.step") == 0

    global_injector.arm("x.point", value=42, times=2)
    assert global_injector.fire("x.point") == 42
    assert global_injector.armed("x.point")
    assert global_injector.fire("x.point") == 42
    # times exhausted -> auto-disarmed, count survives.
    assert not global_injector.armed("x.point")
    assert global_injector.fire("x.point") is None
    assert global_injector.fired("x.point") == 2

    with inject("y.point", RuntimeError, times=None):
        with pytest.raises(RuntimeError, match="injected fault at 'y.point'"):
            global_injector.fire("y.point")
    # Context exit disarms even with times=None.
    assert global_injector.fire("y.point") is None


def test_injector_probability_is_seeded_and_partial():
    def run(seed):
        reg = FaultInjector(seed=seed)
        reg.arm("p", value=1, times=None, probability=0.5)
        return [reg.fire("p") for _ in range(200)]

    fires = sum(v == 1 for v in run(7))
    assert 40 < fires < 160  # partial, not all-or-nothing
    assert run(7) == run(7)  # reproducible chaos soaks


def test_injector_delay_blocks_then_returns():
    global_injector.arm("d", delay=0.05, value="v")
    t0 = time.perf_counter()
    assert global_injector.fire("d") == "v"
    assert time.perf_counter() - t0 >= 0.05


# ----------------------------- breaker --------------------------------- #

def test_breaker_opens_after_threshold_and_fast_fails():
    br = CircuitBreaker(failure_threshold=3, recovery_timeout=30.0, name="t1")
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == "closed"
    assert br.allow()
    br.record_failure()  # third consecutive -> open
    assert br.state == "open"
    assert not br.allow()
    assert br.retry_after() > 0
    err = br.open_error()
    assert isinstance(err, CircuitOpenError) and err.retry_after > 0


def test_breaker_half_open_probe_paths():
    br = CircuitBreaker(
        failure_threshold=1, recovery_timeout=0.05, half_open_max=1, name="t2"
    )
    br.record_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.state == "half_open"
    assert br.allow()       # the probe slot
    assert not br.allow()   # only half_open_max probes pass
    br.record_failure()     # probe failed -> re-open, window re-armed
    assert br.state == "open"
    time.sleep(0.06)
    assert br.allow()
    br.record_success()     # probe succeeded -> closed
    assert br.state == "closed" and br.allow()


def test_breaker_released_probe_does_not_wedge_half_open():
    # A probe that ends with NO verdict (cancelled mid-flight) must give
    # its slot back — leaked slots would pin allow() False forever.
    br = CircuitBreaker(
        failure_threshold=1, recovery_timeout=0.05, half_open_max=1, name="t4"
    )
    br.record_failure()
    time.sleep(0.06)
    assert br.allow()        # probe reserved...
    br.release_probe()       # ...but the call was cancelled: release
    assert br.allow()        # the slot is available again
    br.record_success()
    assert br.state == "closed"


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=2, name="t3")
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # never 2 consecutive


# ------------------------- handler reliability -------------------------- #

def _handler(backend, **rel_kw):
    cfg_kw = {
        k: rel_kw.pop(k)
        for k in ("retries", "retry_delay", "timeout")
        if k in rel_kw
    }
    return LLMHandler(
        LLMConfig(
            provider="mock",
            reliability=ReliabilityConfig(**rel_kw),
            **cfg_kw,
        ),
        backend=backend,
    )


def test_backoff_is_exponential_capped_and_jittered():
    h = _handler(
        MockBackend(), retries=0, retry_delay=1.0,
        retry_max_delay=4.0, retry_jitter=False,
    )
    assert [h._backoff_delay(a) for a in range(4)] == [1.0, 2.0, 4.0, 4.0]
    hj = _handler(
        MockBackend(), retries=0, retry_delay=1.0, retry_max_delay=4.0,
    )
    for attempt, base in enumerate([1.0, 2.0, 4.0, 4.0]):
        for _ in range(20):
            d = hj._backoff_delay(attempt)
            assert 0.5 * base <= d <= base


@pytest.mark.asyncio
async def test_handler_breaker_opens_then_recovers_half_open():
    calls = {"n": 0, "healthy": False}

    class Flaky(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            if not calls["healthy"]:
                raise RuntimeError("device gone")
            return await super().generate(messages, tools, params)

    h = _handler(
        Flaky(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=2, breaker_recovery_timeout=0.1,
    )
    for _ in range(2):
        with pytest.raises(RuntimeError):
            await h.apredict("x")
    assert calls["n"] == 2 and h.breaker.state == "open"
    # Open -> fast fail without touching the backend.
    with pytest.raises(CircuitOpenError):
        await h.apredict("x")
    assert calls["n"] == 2
    # Recovery window -> half-open probe -> success closes it.
    calls["healthy"] = True
    await asyncio.sleep(0.12)
    assert await h.apredict("x")
    assert h.breaker.state == "closed" and calls["n"] == 3


@pytest.mark.asyncio
async def test_handler_timeout_injection_feeds_breaker():
    """Breaker open -> fast-fail -> half-open recovery, driven purely by
    the injection registry (acceptance criterion)."""
    backend_calls = {"n": 0}

    class Counting(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            backend_calls["n"] += 1
            return await super().generate(messages, tools, params)

    h = _handler(
        Counting(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=2, breaker_recovery_timeout=0.1,
    )
    global_injector.arm("handler.timeout", asyncio.TimeoutError, times=2)
    for _ in range(2):
        with pytest.raises(RuntimeError, match="failed after 1 attempt"):
            await h.apredict("x")
    assert backend_calls["n"] == 0  # fault fired before the backend
    assert h.breaker.state == "open"
    with pytest.raises(CircuitOpenError):
        await h.apredict("x")
    await asyncio.sleep(0.12)
    assert await h.apredict("x")  # injection exhausted -> probe succeeds
    assert h.breaker.state == "closed"
    assert global_injector.fired("handler.timeout") == 2


@pytest.mark.asyncio
async def test_handler_deadline_preempts_backend_and_backoff():
    calls = {"n": 0}

    class Slow(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            await asyncio.sleep(0.5)
            return await super().generate(messages, tools, params)

    h = _handler(Slow(), retries=3, retry_delay=5.0, breaker_enabled=False)
    # Born expired: no backend call at all.
    with pytest.raises(DeadlineExceeded):
        await h.apredict(
            "x", params=GenerationParams(deadline=time.monotonic() - 1)
        )
    assert calls["n"] == 0
    # Deadline clips the wait: fails in ~0.1s, and the 5s backoff must
    # not be slept through either (the deadline pre-empts the retry).
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceeded):
        await h.apredict(
            "x", params=GenerationParams(deadline=time.monotonic() + 0.1)
        )
    assert time.perf_counter() - t0 < 0.45
    assert calls["n"] == 1


@pytest.mark.asyncio
async def test_handler_overload_is_not_retried_and_not_breaker_failure():
    calls = {"n": 0}

    class Shedding(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            raise EngineOverloaded("queue full")

    h = _handler(
        Shedding(), retries=3, retry_delay=0.0, breaker_failure_threshold=1,
    )
    with pytest.raises(EngineOverloaded):
        await h.apredict("x")
    assert calls["n"] == 1  # no retry: push-back means push-back
    assert h.breaker.state == "closed"  # shed != device failure


@pytest.mark.asyncio
async def test_astream_shed_is_not_a_breaker_failure():
    class SheddingStream(MockBackend):
        async def generate_stream(
            self, messages, tools=None, params=None, info=None
        ):
            raise EngineOverloaded("stream shed")
            yield  # pragma: no cover — makes this an async generator

    h = _handler(SheddingStream(), retries=0, breaker_failure_threshold=1)
    with pytest.raises(EngineOverloaded):
        async for _ in h.astream("x"):
            pass
    assert h.breaker.state == "closed"  # unary-path parity: shed != failure


# --------------------------- batcher chaos ------------------------------ #

def test_deadline_bounds_request_against_slow_engine():
    """Acceptance: a short deadline against a chaos-slowed engine returns
    a structured timeout error and the slot is NOT leaked (n_slots=1 —
    the follow-up request can only complete through the freed slot)."""
    b = _tiny_batcher(n_slots=1)
    b.start()
    try:
        with inject("engine.prefill", delay=0.3, times=None):
            req = GenRequest(
                prompt_ids=[3, 4, 5], max_new_tokens=48,
                deadline=time.monotonic() + 0.1,
            )
            fut = b.submit(req)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=120)
        assert global_injector.fired("engine.prefill") >= 1
        # Slot freed: a fresh request (no deadline) completes through it.
        req2 = GenRequest(prompt_ids=[6, 7], max_new_tokens=4)
        out = b.submit(req2).result(timeout=120)
        assert isinstance(out, list) and len(out) >= 1
        assert b._thread.is_alive()
    finally:
        b.stop()


def test_deadline_expired_in_backlog_rejected_at_admission():
    b = _tiny_batcher(n_slots=1)
    req = GenRequest(
        prompt_ids=[3, 4], max_new_tokens=4,
        deadline=time.monotonic() + 0.05,
    )
    fut = b.submit(req)  # queued while the loop isn't running yet
    time.sleep(0.1)
    before = global_metrics.get("engine.expired")
    b.start()
    try:
        with pytest.raises(DeadlineExceeded, match="before admission"):
            fut.result(timeout=60)
        assert global_metrics.get("engine.expired") >= before + 1
    finally:
        b.stop()


def test_deadline_expired_before_submit_costs_nothing():
    b = _tiny_batcher(n_slots=1)  # never started: submit path only
    req = GenRequest(
        prompt_ids=[3], max_new_tokens=4, deadline=time.monotonic() - 1,
    )
    fut = b.submit(req)
    with pytest.raises(DeadlineExceeded, match="before submit"):
        fut.result(timeout=1)
    assert b.queue_depth() == 0  # no queue entry exists for it


def test_queue_depth_shedding_while_inflight_completes():
    """Acceptance: submits beyond max_queue_depth raise EngineOverloaded
    (the HTTP edge maps it to 429) while already-accepted requests
    complete untouched."""
    b = _tiny_batcher(n_slots=1, max_queue_depth=2)
    futs = [
        b.submit(GenRequest(prompt_ids=[3, 4], max_new_tokens=3))
        for _ in range(2)
    ]
    assert b.saturated()
    with pytest.raises(EngineOverloaded, match="shedding"):
        b.submit(GenRequest(prompt_ids=[5], max_new_tokens=3))
    assert global_metrics.get("engine.shed") >= 1
    b.start()
    try:
        for fut in futs:  # the accepted work still completes
            assert isinstance(fut.result(timeout=120), list)
    finally:
        b.stop()


def test_injected_step_failure_fails_occupied_not_queued():
    """Chaos regression for the device-failure path with recovery OFF
    (recovery_max_attempts=0, the pre-0.10 contract): the in-flight
    request fails with the ORIGINAL exception; the queued request
    survives and completes."""
    b = _tiny_batcher(n_slots=1, recovery_max_attempts=0)
    global_injector.arm(
        "engine.step", RuntimeError("injected device failure"), times=1
    )
    b.start()
    try:
        fut1 = b.submit(GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=32))
        fut2 = b.submit(GenRequest(prompt_ids=[6, 7], max_new_tokens=4))
        with pytest.raises(RuntimeError, match="injected device failure"):
            fut1.result(timeout=120)
        out = fut2.result(timeout=120)  # queued work survived the failure
        assert isinstance(out, list) and len(out) >= 1
        assert b._thread.is_alive() and b._reader.is_alive()
        assert global_injector.fired("engine.step") == 1
    finally:
        b.stop()


@pytest.mark.slow
def test_chaos_soak_probabilistic_step_failures():
    """Soak (chaos lane only): every request resolves — result or the
    injected error — under randomized dispatch failures, and the engine
    stays serviceable afterwards."""
    b = _tiny_batcher(n_slots=2)
    b.start()
    try:
        with inject(
            "engine.step", RuntimeError("soak fault"),
            times=None, probability=0.3,
        ):
            futs = [
                b.submit(GenRequest(
                    prompt_ids=[3 + i, 4, 5], max_new_tokens=8, seed=i,
                ))
                for i in range(12)
            ]
            resolved = 0
            for fut in futs:
                try:
                    assert isinstance(fut.result(timeout=180), list)
                except RuntimeError as exc:
                    assert "soak fault" in str(exc)
                resolved += 1
            assert resolved == 12
        out = b.submit(
            GenRequest(prompt_ids=[9, 9], max_new_tokens=4)
        ).result(timeout=120)
        assert isinstance(out, list)
    finally:
        b.stop()


# ----------------------- engine fault domain ---------------------------- #
# In-flight recovery, the device watchdog, poison containment and the
# degradation ladder (ISSUE 9). Everything here drives the failure paths
# through the named injection registry — no monkeypatching.


def test_injected_step_failure_recovers_in_flight_byte_identical():
    """Acceptance: an injected engine.step failure mid-decode → every
    in-flight request completes with byte-identical greedy output vs an
    uninjected run, zero client-visible errors, engine.rebuilds == 1."""
    from pilottai_tpu.obs import global_blackbox

    b = _tiny_batcher(n_slots=2)
    b.start()
    try:
        prompts = [[3, 4, 5], [6, 7]]
        ref = [
            b.submit(GenRequest(prompt_ids=list(p), max_new_tokens=12))
            .result(timeout=120)
            for p in prompts
        ]
        before = global_metrics.get("engine.rebuilds")
        global_injector.arm(
            "engine.step", RuntimeError("injected device failure"), times=1
        )
        futs = [
            b.submit(GenRequest(prompt_ids=list(p), max_new_tokens=12))
            for p in prompts
        ]
        got = [f.result(timeout=120) for f in futs]  # no client errors
        assert got == ref
        assert global_injector.fired("engine.step") == 1
        assert global_metrics.get("engine.rebuilds") == before + 1
        assert global_metrics.get("engine.recovered_requests") >= 1
        # Satellite: the failure-path rebuild writes a black-box dump
        # and counts under engine.rebuilds{reason=} (was log-lines only).
        assert any(
            r["reason"] == "engine_rebuild" for r in global_blackbox.recent(20)
        )
        assert global_metrics.get("engine.rebuilds.device_loop_error") >= 1
    finally:
        b.stop()


def test_recovery_replays_folded_tokens_and_streams_without_duplicates():
    """Mid-decode fault AFTER tokens already streamed: the re-admission
    re-prefills over prompt+generated (tokens_replayed counts them), the
    stream resumes at the next NEW token (no duplicates — the collected
    stream equals the final result), and greedy output matches the
    uninjected run."""
    b = _tiny_batcher(n_slots=1)
    b.start()
    try:
        ref = b.submit(
            GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=64)
        ).result(timeout=120)
        before = global_metrics.get("engine.tokens_replayed")
        got: list = []
        req = GenRequest(
            prompt_ids=[3, 4, 5], max_new_tokens=64,
            on_tokens=lambda ids: got.extend(ids),
        )
        fut = b.submit(req)
        # Wait until real tokens have folded, THEN break the device.
        t_end = time.time() + 60
        while time.time() < t_end and not got:
            time.sleep(0.005)
        assert got, "no tokens streamed before arming the fault"
        global_injector.arm(
            "engine.step", RuntimeError("mid-decode device failure"), times=1
        )
        out = fut.result(timeout=120)
        assert out == ref
        assert got == out  # stream == result: nothing duplicated or lost
        assert global_metrics.get("engine.tokens_replayed") > before
        assert req.recovery_attempts == 1
    finally:
        b.stop()


def test_recovery_strikes_exhausted_fails_with_original_exception():
    """N strikes → the ORIGINAL exception surfaces, and the engine stays
    serviceable for new work afterwards."""
    b = _tiny_batcher(n_slots=1, recovery_max_attempts=2)
    b.start()
    try:
        before = global_metrics.get("engine.recovery_failed")
        with inject(
            "engine.step", RuntimeError("persistent device failure"),
            times=None,
        ):
            fut = b.submit(GenRequest(prompt_ids=[3, 4], max_new_tokens=8))
            with pytest.raises(RuntimeError, match="persistent device"):
                fut.result(timeout=120)
        assert global_metrics.get("engine.recovery_failed") >= before + 1
        out = b.submit(
            GenRequest(prompt_ids=[5, 6], max_new_tokens=4)
        ).result(timeout=120)
        assert isinstance(out, list) and len(out) >= 1
    finally:
        b.stop()


def test_prefill_dispatch_failure_unwinds_prep_and_recovers():
    """Satellite: injected ``engine.prefill`` failure against a
    _PreparedAdmission mid-flight — slot reservations (``_prep_reserved``)
    and allocated pages fully release (no leak), admission resumes, and
    the group's requests complete via bounded re-admission."""
    b = _tiny_batcher(
        n_slots=2, paged=True, page_size=16, overlap_admission=True,
    )
    before = global_metrics.get("engine.recovery_requeued")
    global_injector.arm(
        "engine.prefill", RuntimeError("injected prefill fault"), times=1
    )
    b.start()
    try:
        futs = [
            b.submit(GenRequest(prompt_ids=[3 + i, 4, 5], max_new_tokens=6))
            for i in range(2)
        ]
        for fut in futs:
            out = fut.result(timeout=120)
            assert isinstance(out, list) and len(out) >= 1
        assert global_injector.fired("engine.prefill") == 1
        assert global_metrics.get("engine.recovery_requeued") >= before + 1
        # Resources fully unwound once everything completed: no leaked
        # reservation (admission would wedge) and no leaked pages (the
        # pool would shrink forever).
        t_end = time.time() + 30
        while time.time() < t_end and (
            b._prep_reserved or b.alloc.free_pages < b.num_pages - 1
        ):
            time.sleep(0.05)
        assert b._prep_reserved == set()
        assert b.alloc.free_pages == b.num_pages - 1
    finally:
        b.stop()


def test_fold_corruption_poisons_only_affected_request():
    """Poison containment: an injected out-of-vocab fold fails ONLY the
    affected request (PoisonedOutput); the other occupant completes and
    the engine stays serviceable."""
    b = _tiny_batcher(n_slots=2)
    b.start()
    try:
        r1 = GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=48)
        r2 = GenRequest(prompt_ids=[6, 7], max_new_tokens=48)
        f1, f2 = b.submit(r1), b.submit(r2)
        # Wait for both to occupy slots, then poison r2's slot.
        t_end = time.time() + 60
        idx = None
        while time.time() < t_end and idx is None:
            idx = next(
                (
                    i for i, s in enumerate(b._slots)
                    if s is not None and s.request is r2
                ),
                None,
            )
            time.sleep(0.005)
        assert idx is not None
        before = global_metrics.get("engine.poisoned")
        global_injector.arm("engine.fold.corrupt", value=idx, times=1)
        with pytest.raises(PoisonedOutput, match="out-of-vocab"):
            f2.result(timeout=120)
        out = f1.result(timeout=120)  # the other occupant is untouched
        assert isinstance(out, list) and len(out) >= 1
        assert global_metrics.get("engine.poisoned") == before + 1
        out2 = b.submit(
            GenRequest(prompt_ids=[9, 9], max_new_tokens=4)
        ).result(timeout=120)
        assert isinstance(out2, list)
    finally:
        b.stop()


# ----------------------------- watchdog --------------------------------- #


def test_watchdog_unit_trip_and_recover():
    """Deterministic (fake-clock) watchdog semantics: idle never trips;
    stale heartbeats WITH work trip (breaker force-opened via the health
    registry, on_stall fired); a late beat recovers."""
    health = EngineHealth()
    br = CircuitBreaker(name="wd-unit")
    health.subscribe(br.on_engine_stall)
    stalls: list = []
    busy = {"v": False}
    t = {"now": 0.0}
    wd = Watchdog(
        stall_s=1.0, has_work=lambda: busy["v"],
        on_stall=stalls.append, health=health,
        clock=lambda: t["now"], poll_s=0.005,
    )
    wd.start()
    try:
        def wait_for(cond, timeout=5.0):
            end = time.time() + timeout
            while time.time() < end and not cond():
                time.sleep(0.005)
            assert cond()

        t["now"] = 50.0  # huge clock jump while IDLE: never a stall
        time.sleep(0.05)
        assert health.healthy()
        busy["v"] = True
        t["now"] = 50.5  # busy but not stale yet
        time.sleep(0.05)
        assert health.healthy()
        t["now"] = 52.0  # stale with work in flight → stalled
        wait_for(lambda: not health.healthy())
        assert br.state == "open"
        assert stalls and stalls[0]["stall_s"] == 1.0
        assert global_metrics.get("engine.watchdog_stalls") >= 1
        wd.beat()  # the hang resolved
        wait_for(health.healthy)
    finally:
        wd.stop()


def test_watchdog_trips_on_hung_dispatch_then_engine_recovers():
    """Acceptance: an injected dispatch hang (a stuck XLA call — never
    raises, never reaches an except arm) trips the watchdog within
    stall_s + grace: health flips, the subscribed breaker force-opens,
    a black-box dump is written. When the hang resolves the request
    still completes and health recovers."""
    from pilottai_tpu.obs import global_blackbox

    b = _tiny_batcher(n_slots=1, watchdog_stall_s=0.5)
    b.start()
    try:
        # Prime: compiles the admission + decode executables so the
        # injected phase measures the hang, not the compiler.
        b.submit(
            GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=8)
        ).result(timeout=120)
        global_engine_health.reset()  # drop any compile-phase stall
        br = CircuitBreaker(name="wd-hang")
        global_engine_health.subscribe(br.on_engine_stall)
        before = global_metrics.get("engine.watchdog_stalls")
        global_injector.arm("engine.dispatch.hang", delay=2.5, times=1)
        fut = b.submit(GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=8))
        # Trip within stall_s + grace (poll granularity + scheduling).
        t_end = time.time() + 2.0
        while time.time() < t_end and global_engine_health.healthy():
            time.sleep(0.01)
        assert not global_engine_health.healthy()
        assert global_engine_health.snapshot()["retry_after"] > 0
        assert global_metrics.get("engine.watchdog_stalls") >= before + 1
        # The subscriber fires right after the health flip — poll
        # briefly rather than racing mark_stalled's callback loop.
        t_end = time.time() + 2.0
        while time.time() < t_end and br.state != "open":
            time.sleep(0.01)
        assert br.state == "open"  # new requests now fast-fail 503
        assert any(
            r["reason"] == "watchdog_stall"
            for r in global_blackbox.recent(20)
        )
        # The hang resolves: the request completes and health recovers.
        out = fut.result(timeout=120)
        assert isinstance(out, list) and len(out) >= 1
        t_end = time.time() + 5.0
        while time.time() < t_end and not global_engine_health.healthy():
            time.sleep(0.01)
        assert global_engine_health.healthy()
    finally:
        b.stop()


@pytest.mark.asyncio
async def test_healthz_and_chat_503_when_engine_stalled():
    """HTTP surface of a stall: /healthz flips to 503 with retry_after;
    the handler's breaker (subscribed at construction) force-opens so
    chat requests fast-fail 503 with retry_after."""
    from pilottai_tpu.server import APIServer

    h = _handler(MockBackend(), breaker_recovery_timeout=60.0)
    server = await APIServer(h).start()
    try:
        status, _ = await _request(server.port, "GET", "/healthz")
        assert status == 200
        global_engine_health.mark_stalled(
            reason="device loop heartbeat stale (test)", retry_after=2.5,
        )
        status, data = await _request(server.port, "GET", "/healthz")
        assert status == 503
        assert data["status"] == "stalled"
        assert data["retry_after"] == 2.5
        assert "stale" in data["reason"]
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
        )
        assert status == 503
        assert data["error"]["type"] == "overloaded_error"
        assert data["error"]["retry_after"] > 0
        global_engine_health.mark_recovered()
        status, _ = await _request(server.port, "GET", "/healthz")
        assert status == 200
    finally:
        await server.stop()


# ------------------------- degradation ladder --------------------------- #


def test_degrade_ladder_steps_and_promotes_on_clean_soak():
    t = {"now": 0.0}
    lad = DegradeLadder(
        fault_threshold=2, window_s=10.0, promote_s=30.0,
        clock=lambda: t["now"],
    )
    assert lad.level() == 0
    lad.record_fault("a")
    assert lad.level() == 0  # below threshold
    lad.record_fault("b")
    assert lad.level() == 1  # burst crossed the threshold
    lad.record_fault("c")
    lad.record_fault("d")
    assert lad.level() == 2  # each rung needs a fresh burst
    # Faults outside the rolling window never accumulate into a step.
    t["now"] = 100.0
    lad.record_fault("e")
    t["now"] = 120.0  # > window_s later
    lad.record_fault("f")
    assert lad.level() <= 2
    # Clean soak: one rung back per promote_s period.
    t["now"] = 300.0
    assert lad.level() == 0
    # Disabled ladder counts faults but never steps.
    off = DegradeLadder(fault_threshold=1, enabled=False)
    off.record_fault("x")
    off.record_fault("y")
    assert off.level() == 0


def test_degrade_rungs_cap_chunks_slots_and_shed_batch():
    """Batcher integration: rung 2 clamps dispatches to the smallest
    compiled chunk bucket, rung 3 halves admissible slots, rung 4 sheds
    batch-class submits outright while interactive still queues."""
    from pilottai_tpu.engine.batcher import _Slot

    lad = DegradeLadder(fault_threshold=1, window_s=60.0, promote_s=3600.0)
    b = _tiny_batcher(n_slots=4, degrade=lad, max_queue_depth=16)
    # Rung 2: a slot needing ~100 tokens would normally take the largest
    # bucket; degraded it takes the smallest.
    b._slots[0] = _Slot(
        request=GenRequest(prompt_ids=[1, 2], max_new_tokens=100),
        prompt_len=2,
    )
    assert b._pick_chunk_blocks() == b.chunk_buckets[-1]
    lad.record_fault("t")
    lad.record_fault("t")
    assert lad.level() == 2
    assert b._pick_chunk_blocks() == b.chunk_buckets[0]
    b._slots[0] = None
    # Rung 3: selection caps occupancy at n_slots // 2.
    lad.record_fault("t")
    assert lad.level() == 3
    for i in range(4):
        b._backlog.append(GenRequest(prompt_ids=[3 + i], max_new_tokens=4))
    groups, seg, _epoch = b._select_groups()
    assert seg is None
    assert sum(len(g) for _, g in groups) == 2
    for _, g in groups:  # unwind the white-box selection
        for idx, req in g:
            b._prep_reserved.discard(idx)
    b._backlog.clear()
    # Rung 4: batch sheds outright (empty queue!), interactive queues.
    lad.record_fault("t")
    assert lad.level() == 4
    before = global_metrics.get("engine.shed.batch")
    with pytest.raises(EngineOverloaded, match="shedding batch-class"):
        b.submit(GenRequest(
            prompt_ids=[5], max_new_tokens=2, slo_class="batch",
        ))
    assert global_metrics.get("engine.shed.batch") == before + 1
    fut = b.submit(GenRequest(prompt_ids=[5], max_new_tokens=2))
    assert not fut.done()  # interactive accepted (engine not started)


def test_batch_class_sheds_at_lower_queue_depth():
    """Satellite: per-SLO-class shed thresholds — batch sheds at
    batch_shed_frac × max_queue_depth, interactive at the full depth,
    each counted under engine.shed.<class>."""
    b = _tiny_batcher(n_slots=1, max_queue_depth=4, batch_shed_frac=0.5)
    b.submit(GenRequest(prompt_ids=[1], max_new_tokens=2))
    b.submit(GenRequest(prompt_ids=[2], max_new_tokens=2))
    # Depth 2 == the batch limit (4 × 0.5): batch sheds...
    before = global_metrics.get("engine.shed.batch")
    with pytest.raises(EngineOverloaded, match="batch-class limit 2"):
        b.submit(GenRequest(
            prompt_ids=[3], max_new_tokens=2, slo_class="batch",
        ))
    assert global_metrics.get("engine.shed.batch") == before + 1
    # ...while interactive still gets the remaining depth.
    b.submit(GenRequest(prompt_ids=[4], max_new_tokens=2))
    b.submit(GenRequest(prompt_ids=[5], max_new_tokens=2))
    before_i = global_metrics.get("engine.shed.interactive")
    with pytest.raises(EngineOverloaded, match="interactive-class limit 4"):
        b.submit(GenRequest(prompt_ids=[6], max_new_tokens=2))
    assert global_metrics.get("engine.shed.interactive") == before_i + 1


# ----------------------------- HTTP edge -------------------------------- #

async def _request(port, method, path, body=None, headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n{extra}"
        f"Connection: close\r\n\r\n".encode() + payload
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body_bytes = raw.partition(b"\r\n\r\n")
    status = int(head.split(b"\r\n")[0].split(b" ")[1])
    return status, json.loads(body_bytes) if body_bytes else {}


class _RaisingBackend(MockBackend):
    def __init__(self, exc):
        super().__init__()
        self._exc = exc

    async def generate(self, messages, tools=None, params=None):
        raise self._exc


@pytest.mark.asyncio
async def test_http_shed_is_429_with_structured_error():
    from pilottai_tpu.server import APIServer

    h = _handler(_RaisingBackend(EngineOverloaded("queue depth 64 at limit")))
    server = await APIServer(h).start()
    try:
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
        )
        assert status == 429
        assert data["error"]["type"] == "overloaded_error"
        assert "queue depth" in data["error"]["message"]
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_deadline_is_408_and_breaker_open_is_503():
    from pilottai_tpu.server import APIServer

    class Slow(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            await asyncio.sleep(0.5)
            return await super().generate(messages, tools, params)

    h = _handler(
        Slow(), retries=0, retry_delay=0.0,
        breaker_failure_threshold=1, breaker_recovery_timeout=60.0,
    )
    server = await APIServer(h).start()
    try:
        # Deadline from the x-request-timeout header -> structured 408.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
            headers={"x-request-timeout": "0.05"},
        )
        assert status == 408
        assert data["error"]["type"] == "timeout_error"
        # That deadline blowout opened the breaker (threshold 1):
        # the next request fast-fails 503 with a retry_after hint.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}]},
        )
        assert status == 503
        assert data["error"]["type"] == "overloaded_error"
        assert data["error"]["retry_after"] > 0
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_timeout_field_validation():
    from pilottai_tpu.server import APIServer

    server = await APIServer(_handler(MockBackend())).start()
    try:
        for bad in ("soon", -1, 0, True):
            status, data = await _request(
                server.port, "POST", "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "timeout": bad},
            )
            assert status == 400, bad
        # A generous valid timeout: request completes normally.
        status, data = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}],
             "timeout": 30},
        )
        assert status == 200
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_task_timeout_is_408():
    from pilottai_tpu.server import APIServer

    class HangingServe:
        async def execute_task(self, task, timeout=None):
            await asyncio.wait_for(asyncio.sleep(60), timeout)

    server = await APIServer(
        _handler(MockBackend()), serve=HangingServe()
    ).start()
    try:
        status, data = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "hangs forever", "timeout": 0.1},
        )
        assert status == 408
        assert data["error"]["type"] == "timeout_error"
    finally:
        await server.stop()


# ------------------------ orchestration chaos --------------------------- #

def _worker(**cfg):
    from pilottai_tpu.core.agent import BaseAgent

    return BaseAgent(
        config=AgentConfig(role="worker", **cfg),
        llm=LLMHandler(LLMConfig(provider="mock")),
    )


@pytest.mark.asyncio
async def test_heartbeat_stall_injection_degrades_health():
    from pilottai_tpu.core.status import HealthStatus
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=60.0, max_recovery_attempts=0,
    ))
    ft.register_agent(agent)
    assert (await ft.check_once())[agent.id] == HealthStatus.HEALTHY
    # Inject a 120s stall: the agent LOOKS silent without being wedged.
    global_injector.arm("agent.heartbeat.stall", value=120.0, times=1)
    assert (await ft.check_once())[agent.id] == HealthStatus.UNHEALTHY
    # Injection consumed -> next pass sees the real (fresh) heartbeat.
    assert (await ft.check_once())[agent.id] == HealthStatus.HEALTHY
    await agent.stop()


@pytest.mark.asyncio
async def test_heartbeat_stall_attributed_as_dag_retry_node():
    """An injected ``agent.heartbeat.stall`` that triggers recovery must
    surface in the affected task's DAG as a ``retry`` node carrying the
    observed stall seconds — chaos-induced dead time is attributed, not
    silently swallowed (obs/dag.py)."""
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.obs import global_dag
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos-dag", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=60.0, max_recovery_attempts=1,
        recovery_cooldown=0.0,
    ))
    ft.register_agent(agent)
    task = Task(description="work interrupted by a stalled heartbeat")
    global_dag.start(task.id, trace_id="chaos-dag-stall-1")
    await agent.add_task(task)
    global_injector.arm("agent.heartbeat.stall", value=120.0, times=1)
    await ft.check_once()  # UNHEALTHY -> in-place recovery path
    try:
        d = global_dag.describe(task.id)
        assert d is not None
        retries = [
            n for n in d["nodes"]
            if n["kind"] == "retry" and n["name"] == "agent_recovery"
        ]
        assert retries, [n["name"] for n in d["nodes"]]
        # The injected 120 s stall (minus the loop's own wall) is
        # attributed on the retry node.
        assert retries[0]["attributes"]["stall_s"] >= 60.0
        assert retries[0]["attributes"]["agent_id"] == agent.id[:8]
    finally:
        global_dag.finish(task.id, "cancelled")
        await agent.stop()


@pytest.mark.asyncio
async def test_fault_requeue_adapts_to_orchestrator_signature():
    """The requeue kwargs are filtered per-parameter against the
    orchestrator's signature: a `reason`-only orchestrator must not be
    handed stall_s (TypeError → task lost), a **kwargs one gets the
    full attribution, and a bare legacy one gets the task alone."""
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance

    task = Task(description="requeue me")
    calls = []

    class ReasonOnly:
        def agent_list(self):
            return []

        async def requeue_task(self, task, reason=""):
            calls.append(("reason_only", reason))

    class FullKwargs:
        def agent_list(self):
            return []

        async def requeue_task(self, task, reason="", **attrs):
            calls.append(("full", reason, attrs))

    class Legacy:
        def agent_list(self):
            return []

        async def requeue_task(self, task):
            calls.append(("legacy",))

    for orch in (ReasonOnly(), FullKwargs(), Legacy()):
        ft = FaultTolerance(orch, FaultToleranceConfig())
        await ft._requeue(task, stall_s=12.0)
    assert calls == [
        ("reason_only", "fault_recovery"),
        ("full", "fault_recovery", {"stall_s": 12.0}),
        ("legacy",),
    ]


@pytest.mark.asyncio
async def test_health_gauge_keyed_by_full_id_and_reaped():
    from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
    from pilottai_tpu.serve import Serve

    agent = _worker()
    await agent.start()
    serve = Serve(name="chaos", agents=[agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(max_recovery_attempts=0))
    await ft.check_once()
    gauges = global_metrics.snapshot()["gauges"]
    assert f"fault.health.{agent.id}" in gauges  # full id, not id[:8]
    assert f"fault.health.{agent.id[:8]}" not in gauges
    # Agent leaves the pool -> record AND gauge reaped.
    serve.agents.pop(agent.id)
    await ft.check_once()
    gauges = global_metrics.snapshot()["gauges"]
    assert f"fault.health.{agent.id}" not in gauges
    assert agent.id not in ft.health
    await agent.stop()


@pytest.mark.asyncio
async def test_execute_task_timeout_threads_into_task_timeout():
    from pilottai_tpu.serve import Serve

    agent = _worker()
    serve = Serve(
        name="chaos", agents=[agent],
        manager_llm=LLMHandler(LLMConfig(provider="mock")),
        config=ServeConfig(max_concurrent_tasks=2),
    )
    await serve.start()
    try:
        result = await serve.execute_task("trivial thing", timeout=7.5)
        assert result.success
        task = next(
            t for t in serve.all_tasks.values()
            if t.description == "trivial thing"
        )
        assert task.timeout == 7.5  # agents see the caller's budget
    finally:
        await serve.stop()


def test_journal_write_failure_degrades_not_crashes(tmp_path):
    from pilottai_tpu.checkpoint.journal import TaskJournal
    from pilottai_tpu.core.task import Task

    journal = TaskJournal(tmp_path / "j.jsonl")
    before = global_metrics.get("journal.write_failures")
    global_injector.arm("checkpoint.write", OSError("disk full"), times=1)
    journal.record_task(Task(description="survives injected disk failure"))
    assert global_metrics.get("journal.write_failures") == before + 1
    # Disk "recovers": subsequent records land and replay sees them.
    t2 = Task(description="after recovery")
    journal.record_task(t2)
    journal.close()
    assert t2.id in TaskJournal.replay(journal.path)
