"""Task-DAG tracing (obs/dag.py): critical-path attribution, breakdown
reconciliation, trace continuity across retries, per-agent occupancy
gauges, /dag.json on both HTTP surfaces, and Perfetto critical-path
flagging — the orchestration layer's observability story."""

import asyncio
import json
import time

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.obs import export_completeness
from pilottai_tpu.obs.dag import (
    BREAKDOWN_COMPONENTS,
    AgentOccupancy,
    DagLedger,
    global_dag,
    global_occupancy,
)
from pilottai_tpu.serve import Serve
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics
from pilottai_tpu.utils.tracing import Tracer, global_tracer


def _mock_llm(**kwargs) -> LLMHandler:
    return LLMHandler(LLMConfig(provider="mock"), backend=MockBackend(**kwargs))


def _serve(llm, agents=None, **cfg) -> Serve:
    cfg.setdefault("decomposition_enabled", False)
    return Serve(
        name="dag-test", manager_llm=llm,
        agents=agents or [BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(**cfg),
    )


def _components_sum(breakdown) -> float:
    return sum(
        breakdown[c] for c in BREAKDOWN_COMPONENTS if c != "straggler_s"
    )


# ---------------------------------------------------------------------- #
# Ledger arithmetic on synthetic DAGs (no engine, no clocks to race)
# ---------------------------------------------------------------------- #


def test_critical_path_follows_dependency_edges():
    """Three subtask branches a, b, c with c depending on a: the chain
    must walk c -> a (its dep), NOT c -> b (the overlapping sibling),
    and the scheduling gap between a and c lands in overhead."""
    ledger = DagLedger(registry=MetricsRegistry(), tracer=Tracer())
    dag = ledger.start("t1", trace_id="tr1")
    t0 = dag.created
    a = dag.add_node("subtask", "a", t0 + 0.0, end=t0 + 1.0)
    dag.add_node("subtask", "b", t0 + 0.0, end=t0 + 1.8)
    c = dag.add_node(
        "subtask", "c", t0 + 2.0, end=t0 + 3.0, deps=[a.node_id]
    )
    dag.ended = t0 + 3.0
    dag.compute()
    critical_ids = [
        s["node_id"] for s in dag.critical_spans if s["kind"] == "subtask"
    ]
    assert critical_ids == [a.node_id, c.node_id]
    # Gap a-end(1.0) -> c-start(2.0) is orchestrator overhead.
    overhead = sum(
        s["duration_s"] for s in dag.critical_spans
        if s["kind"] == "overhead"
    )
    assert overhead == pytest.approx(1.0, abs=1e-6)
    # b (1.8) vs siblings: straggler = max - median of [1.0, 1.8, 1.0].
    assert dag.breakdown["straggler_s"] == pytest.approx(0.8, abs=1e-6)
    # Critical path covers e2e exactly on a closed ledger.
    assert dag.breakdown["critical_path_s"] == pytest.approx(
        3.0, abs=1e-6
    )


def test_flight_split_and_breakdown_components_sum():
    """A flight's critical time splits into queue/prefill/decode by its
    own phase shares, and the non-straggler components sum to the
    critical path (which equals e2e on a closed ledger)."""
    ledger = DagLedger(registry=MetricsRegistry(), tracer=Tracer())
    dag = ledger.start("t2", trace_id="tr2")
    t0 = dag.created
    agent = dag.add_node("agent", "worker", t0 + 0.1, end=t0 + 2.1)
    dag.add_node(
        "flight", "m", t0 + 0.3, end=t0 + 1.3,
        parent_id=agent.node_id,
        queue_wait_s=0.2, prefill_s=0.3, decode_s=0.5,
    )
    dag.add_node(
        "tool", "search", t0 + 1.5, end=t0 + 2.0,
        parent_id=agent.node_id,
    )
    dag.ended = t0 + 2.2
    dag.compute()
    bd = dag.breakdown
    assert bd["queue_wait_s"] == pytest.approx(0.2, abs=1e-6)
    assert bd["llm_prefill_s"] == pytest.approx(0.3, abs=1e-6)
    assert bd["llm_decode_s"] == pytest.approx(0.5, abs=1e-6)
    assert bd["tool_s"] == pytest.approx(0.5, abs=1e-6)
    assert _components_sum(bd) == pytest.approx(
        bd["critical_path_s"], abs=1e-5
    )
    assert bd["critical_path_s"] == pytest.approx(bd["e2e_s"], abs=1e-5)


def test_subtask_rollup_merges_child_breakdown():
    """A finished subtask rolls up into its parent's dag as a node whose
    breakdown attribute redistributes the child's span on the parent's
    critical path (LLM time stays LLM time through the rollup)."""
    registry = MetricsRegistry()
    ledger = DagLedger(registry=registry, tracer=Tracer())
    parent_dag = ledger.start("parent", trace_id="tr3")
    child_dag = ledger.start(
        "child", trace_id="tr3", parent_task_id="parent"
    )
    t0 = child_dag.created
    child_dag.add_node(
        "flight", "m", t0, end=t0 + 1.0,
        queue_wait_s=0.0, prefill_s=0.5, decode_s=0.5,
    )
    child_dag.ended = t0 + 1.0  # synthetic clock: pre-stamp both ends
    summary = ledger.finish("child", "ok")
    assert summary["breakdown"]["llm_prefill_s"] == pytest.approx(
        0.5, abs=1e-5
    )
    parent_dag.ended = t0 + 1.05
    parent_summary = ledger.finish("parent", "ok")
    # The child covered ~all of the parent's life, so the parent's
    # breakdown is dominated by the child's LLM components.
    bd = parent_summary["breakdown"]
    assert bd["llm_prefill_s"] > 0.3
    assert bd["llm_decode_s"] > 0.3
    # task.* histograms observed twice (child + parent).
    hists = registry.snapshot()["histograms"]
    assert hists["task.e2e_s"]["count"] == 2


def test_dag_node_cap_counts_overflow():
    """A runaway task must not grow its ledger unboundedly: past
    MAX_NODES, nodes are dropped and counted, not silently kept."""
    ledger = DagLedger(registry=MetricsRegistry(), tracer=Tracer())
    dag = ledger.start("cap", trace_id="cap")
    t0 = dag.created
    for i in range(dag.MAX_NODES + 5):
        dag.add_node("tool", f"n{i}", t0, end=t0 + 0.001)
    assert len(dag.nodes) == dag.MAX_NODES
    assert dag.dropped_nodes == 5
    dag.ended = t0 + 0.01
    summary = ledger.finish("cap", "ok")
    assert summary["dropped_nodes"] == 5


# ---------------------------------------------------------------------- #
# Serve integration (mock engine)
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_serve_task_dag_reconciles_and_nests():
    llm = _mock_llm()
    serve = _serve(llm)
    await serve.start()
    try:
        task = serve.prepare_task("count the widgets")
        t0 = time.perf_counter()
        result = await serve.execute_task(task)
        wall = time.perf_counter() - t0
        assert result.success
        d = global_dag.describe(task.id)
        assert d is not None and d["status"] == "ok"
        bd = d["breakdown"]
        # Reconciliation: critical-path sum ~= ledger e2e (15% bar) and
        # ledger e2e ~= the caller-observed wall.
        assert bd["critical_path_s"] == pytest.approx(
            bd["e2e_s"], rel=0.15
        )
        assert bd["e2e_s"] <= wall * 1.15
        # Components sum to >= 90% of e2e.
        assert _components_sum(bd) >= 0.9 * bd["e2e_s"]
        kinds = {(n["kind"], n["name"]) for n in d["nodes"]}
        assert ("stage", "analyze") in kinds
        assert ("stage", "route") in kinds
        assert ("queue", "task_queue") in kinds
        assert ("agent", "worker") in kinds
        # Engine flights joined and nested under the agent node.
        agent_ids = {
            n["node_id"] for n in d["nodes"] if n["kind"] == "agent"
        }
        flights = [n for n in d["nodes"] if n["kind"] == "flight"]
        assert flights and any(
            f["parent_id"] in agent_ids for f in flights
        )
        # Queue wait observed, by priority too.
        hists = global_metrics.snapshot()["histograms"]
        assert hists["task.queue_wait.normal_s"]["count"] >= 1
    finally:
        await serve.stop()


def _force_decomposition(prompt):
    if '"requires_decomposition"' in prompt:
        return {"requires_decomposition": True, "complexity": 7,
                "estimated_resources": {}}
    return None  # protocol defaults (3 subtasks with dependencies)


@pytest.mark.asyncio
async def test_fanout_dag_rollup_one_trace():
    llm = _mock_llm(responders=[_force_decomposition])
    serve = _serve(llm, decomposition_enabled=True)
    await serve.start()
    try:
        task = serve.prepare_task("produce the annual report")
        result = await serve.execute_task(task, timeout=60)
        assert result.success
        d = global_dag.describe(task.id)
        subtasks = [n for n in d["nodes"] if n["kind"] == "subtask"]
        assert len(subtasks) >= 3
        # Dependency edges resolved between sibling subtask nodes.
        assert any(n["deps"] for n in subtasks)
        # One task tree = one trace: every subtask dag carries the
        # parent's trace id.
        sub_ids = result.metadata["subtask_ids"]
        for sid in sub_ids:
            sub = global_dag.describe(sid)
            assert sub is not None and sub["trace_id"] == d["trace_id"]
        bd = d["breakdown"]
        assert bd["critical_path_s"] == pytest.approx(
            bd["e2e_s"], rel=0.15
        )
        assert _components_sum(bd) >= 0.9 * bd["e2e_s"]
        # Fan-out ran: LLM time reached the parent through the rollup.
        assert bd["llm_decode_s"] + bd["llm_prefill_s"] > 0
    finally:
        await serve.stop()


def _fail_first_evaluation():
    """Responder: the FIRST agent result_evaluation fails the task, so
    the orchestrator's retry path runs exactly once."""
    state = {"failed": False}

    def responder(prompt):
        if '"success"' in prompt and "issues" in prompt:
            if not state["failed"]:
                state["failed"] = True
                return {"success": False, "issues": ["forced failure"]}
            return {"success": True, "issues": []}
        return None

    return responder


@pytest.mark.asyncio
async def test_retry_attempts_stay_in_one_trace():
    """Regression (trace continuity): a retry attempt must be a child
    span of the original task trace with its attempt index — not a
    fresh ambient trace."""
    llm = _mock_llm(responders=[_fail_first_evaluation()])
    serve = _serve(llm)
    await serve.start()
    try:
        task = serve.prepare_task("flaky work")
        result = await serve.execute_task(task, timeout=30)
        assert result.success
        trace_id = task.metadata["trace_id"]
        spans = global_tracer.for_trace(trace_id)
        names = [s.name for s in spans]
        assert "serve.execute_task" in names
        retry_spans = [s for s in spans if s.name.startswith("retry.")]
        assert retry_spans, names
        assert retry_spans[0].attributes.get("attempt") == 1
        # BOTH agent executions (original + retry) are in this trace.
        agent_spans = [s for s in spans if s.name == "agent.execute_task"]
        assert len(agent_spans) >= 2
        assert {s.trace_id for s in agent_spans} == {trace_id}
        # The dag recorded the retry node with the attempt index.
        d = global_dag.describe(task.id)
        retries = [n for n in d["nodes"] if n["kind"] == "retry"]
        assert retries and retries[0]["attributes"]["attempt"] == 1
        assert global_metrics.get("task.retries") >= 1
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_requeue_keeps_trace_and_records_retry_node():
    llm = _mock_llm()
    serve = _serve(llm)
    await serve.start()
    try:
        task = serve.prepare_task("requeued work")
        await serve.add_task(task)
        trace_id = task.metadata["trace_id"]
        await serve.requeue_task(task, reason="rebalance", stall_s=1.5)
        assert task.metadata["trace_id"] == trace_id  # trace survives
        result = await serve.wait_for(task.id, timeout=30)
        assert result.success
        d = global_dag.describe(task.id)
        requeues = [
            n for n in d["nodes"]
            if n["kind"] == "retry" and n["name"] == "rebalance"
        ]
        assert requeues
        assert requeues[0]["attributes"]["stall_s"] == 1.5
        assert d["trace_id"] == trace_id
    finally:
        await serve.stop()


# ---------------------------------------------------------------------- #
# Export surfaces
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_dag_json_on_api_server_and_dashboard():
    from pilottai_tpu.server import APIServer
    from pilottai_tpu.utils.dashboard import MetricsDashboard
    from tests.test_server import _request

    llm = _mock_llm()
    serve = _serve(llm)
    await serve.start()
    server = await APIServer(llm, serve=serve).start()
    dash = MetricsDashboard().start()
    try:
        task = serve.prepare_task("export me")
        result = await serve.execute_task(task)
        assert result.success
        status, _, body = await _request(server.port, "GET", "/dag.json")
        assert status == 200
        snap = json.loads(body)
        assert any(
            f["task_id"] == task.id for f in snap["finished"]
        )
        status, _, body = await _request(
            server.port, "GET", f"/dag.json?task_id={task.id}"
        )
        assert status == 200
        described = json.loads(body)
        assert described["status"] == "ok" and described["nodes"]
        status, _, _ = await _request(
            server.port, "GET", "/dag.json?task_id=nope"
        )
        assert status == 404

        # Dashboard parity (threaded http.server).
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{dash.port}/dag.json", timeout=10
        ) as resp:
            dsnap = json.loads(resp.read())
        assert any(
            f["task_id"] == task.id for f in dsnap["finished"]
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{dash.port}/dag.json?task_id={task.id}",
            timeout=10,
        ) as resp:
            assert json.loads(resp.read())["task_id"] == task.id
        # Unknown task: 404 on the dashboard too (APIServer parity).
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{dash.port}/dag.json?task_id=nope",
                timeout=10,
            )
        assert err.value.code == 404
    finally:
        dash.stop()
        await server.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_perfetto_critical_path_spans_flagged():
    from pilottai_tpu.obs import perfetto_trace

    llm = _mock_llm()
    serve = _serve(llm)
    await serve.start()
    try:
        task = serve.prepare_task("flag my critical path")
        result = await serve.execute_task(task)
        assert result.success
        trace_id = task.metadata["trace_id"]
        spans = global_tracer.for_trace(trace_id)
        critical = [
            s for s in spans if s.attributes.get("critical_path")
        ]
        assert critical  # dag.finish emitted the flagged lane
        assert all(s.name.startswith("dag.critical.") for s in critical)
        trace = perfetto_trace(spans)
        flagged = [
            e for e in trace["traceEvents"]
            if e.get("args", {}).get("critical_path")
        ]
        assert flagged
        # Stage spans + agent + engine spans share the one track.
        names = {
            e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"
        }
        assert "serve.execute_task" in names
        assert "stage.route" in names
        assert "agent.execute_task" in names
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_export_completeness_covers_task_and_agent_series():
    llm = _mock_llm()
    serve = _serve(llm)
    await serve.start()
    try:
        result = await serve.execute_task("wire check")
        assert result.success
    finally:
        await serve.stop()
    declared = global_metrics.declared()
    for series in (
        "task.e2e_s", "task.critical_path_s",
        "task.orchestrator_overhead_s", "task.queue_wait_s",
        "task.llm_prefill_s", "task.llm_decode_s", "task.tool_s",
        "task.straggler_s", "task.queue_wait.normal_s",
        "task.completed", "task.retries", "task.active",
        "agent.worker.busy_frac", "agent.worker.queue_depth",
    ):
        assert series in declared, series
    problems = export_completeness()
    assert problems == [], problems


# ---------------------------------------------------------------------- #
# Agent occupancy
# ---------------------------------------------------------------------- #


def test_occupancy_busy_frac_window_arithmetic():
    registry = MetricsRegistry()
    occ = AgentOccupancy(registry=registry, window_s=10.0)
    occ.register("writer", "a1")
    occ.register("writer", "a2")
    now = time.perf_counter()
    # Fake two closed busy intervals by poking the tracked structures
    # through the public step API (keys distinguish agents).
    occ._since["writer"] = now - 10.0
    occ._busy["writer"].append((now - 8.0, now - 3.0))   # 5 s agent 1
    occ._busy["writer"].append((now - 6.0, now - 1.0))   # 5 s agent 2
    fracs = occ.refresh()
    # 10 busy-seconds over a 10 s window x 2 agents = 0.5.
    assert fracs["writer"] == pytest.approx(0.5, abs=0.05)
    assert registry.snapshot()["gauges"][
        "agent.writer.busy_frac"
    ] == pytest.approx(0.5, abs=0.05)
    occ.set_queue_depth("writer", 3)
    assert registry.snapshot()["gauges"]["agent.writer.queue_depth"] == 3.0


@pytest.mark.asyncio
async def test_agent_execution_drives_busy_frac_gauge():
    llm = _mock_llm(latency=0.05)
    agent = BaseAgent(
        config=AgentConfig(role="busyrole", specializations=["generic"]),
        llm=llm,
    )
    serve = _serve(llm, agents=[agent])
    await serve.start()
    try:
        result = await serve.execute_task("keep the agent busy")
        assert result.success
        fracs = global_occupancy.refresh()
        assert fracs.get("busyrole", 0.0) > 0.0
    finally:
        await serve.stop()
    # stop() retired the role: the gauge zeroes and the role leaves the
    # tracker (a stale role would bias every mean-over-roles consumer).
    assert "busyrole" not in global_occupancy.refresh()
    gauges = global_metrics.snapshot()["gauges"]
    assert gauges["agent.busyrole.busy_frac"] == 0.0


# ---------------------------------------------------------------------- #
# Native CPU engine: acceptance reconciliation + one-trace nesting
# ---------------------------------------------------------------------- #


@pytest.mark.slow  # CI main lane; real-engine boot is a soak, like
@pytest.mark.asyncio  # PR 6's live-vs-profiled MFU reconciliation.
async def test_cpu_engine_fanout_one_trace_and_reconciliation():
    """The acceptance scenario: a Serve fan-out task whose agents run on
    the REAL CPU engine produces ONE Perfetto trace nesting server ->
    orchestrator stages -> agent steps -> engine flights with critical
    spans flagged, and the ledger reconciles (critical path ~= e2e
    within 15%, components >= 90% of e2e). The mock-engine variants
    above keep the same reconciliation bars in the tier-1 lane."""
    from pilottai_tpu.server import APIServer
    from tests.test_server import _request

    engine = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=4, engine_max_seq=128, engine_chunk=4,
    ))
    # Manager decisions are mock-driven (deterministic fan-out into 3
    # dependent subtasks); agent reasoning steps run on the CPU engine.
    manager = _mock_llm(responders=[_force_decomposition])
    serve = Serve(
        name="dag-cpu", manager_llm=manager,
        agents=[BaseAgent(
            config=AgentConfig(
                role="cpuworker", specializations=["generic"],
                max_iterations=2,
            ),
            llm=engine,
        )],
        config=ServeConfig(decomposition_enabled=True,
                           max_concurrent_tasks=4),
    )
    await serve.start()
    server = await APIServer(engine, serve=serve).start()
    try:
        status, headers, body = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "compile the quarterly report", "timeout": 120},
            headers={"x-request-id": "dag-cpu-trace-1"},
        )
        assert status == 200, body
        payload = json.loads(body)
        assert payload["success"], payload
        sub_ids = payload["metadata"]["subtask_ids"]
        assert len(sub_ids) >= 3

        spans = global_tracer.for_trace("dag-cpu-trace-1")
        names = {s.name for s in spans}
        # server -> orchestrator stages -> agent steps -> engine flights,
        # all in the ONE trace the request carried in.
        assert "server.request" in names
        assert "stage.analyze" in names
        assert "serve.execute_task" in names
        assert "agent.execute_task" in names
        assert "engine.generate" in names
        assert "engine.batch_decode" in names  # native batcher span
        assert any(
            s.attributes.get("critical_path") for s in spans
        )

        # Ledger reconciliation on the parent AND every subtask.
        task_id = next(
            d["task_id"] for d in global_dag.finished()
            if d.get("attributes", {}) is not None
            and d["task_id"] not in sub_ids
            and d["trace_id"] == "dag-cpu-trace-1"
            and d["parent_task_id"] is None
        )
        for tid in [task_id] + list(sub_ids):
            d = global_dag.describe(tid)
            assert d is not None, tid
            bd = d["breakdown"]
            assert bd["critical_path_s"] == pytest.approx(
                bd["e2e_s"], rel=0.15
            ), (tid, bd)
            assert _components_sum(bd) >= 0.9 * bd["e2e_s"], (tid, bd)
        # Real engine time was attributed: decode shows up in a subtask.
        sub_bd = global_dag.describe(sub_ids[0])["breakdown"]
        assert sub_bd["llm_decode_s"] + sub_bd["llm_prefill_s"] > 0
    finally:
        await server.stop()
        await serve.stop()
        await engine.stop()
