"""Ring attention (context parallelism) on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.ops.attention import dot_product_attention, make_attention_mask
from pilottai_tpu.parallel.mesh import compat_set_mesh, MeshConfig, create_mesh
from pilottai_tpu.parallel.ring_attention import ring_attention
from pilottai_tpu.train import Trainer, TrainConfig, synthetic_batches


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(MeshConfig(data=2, model=2, seq=2))


@pytest.fixture(scope="module")
def mesh_seq4():
    return create_mesh(MeshConfig(data=2, seq=4))


def _setup(B=4, T=64, N=4, K=2, H=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, T, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    ps = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)).astype(jnp.int32)
    return q, k, v, ps


@pytest.mark.parametrize("window,softcap", [(0, 0.0), (16, 0.0), (0, 30.0)])
def test_ring_matches_reference(mesh, window, softcap):
    q, k, v, ps = _setup()
    T, H = q.shape[1], q.shape[3]
    valid = jnp.asarray([64, 50, 64, 40], jnp.int32)
    mask = make_attention_mask(ps, T, valid, window=window)
    ref = dot_product_attention(
        q, k, v, mask=mask, scale=H**-0.5, logit_softcap=softcap
    )
    with compat_set_mesh(mesh):
        got = jax.jit(
            lambda *a: ring_attention(
                *a, scale=H**-0.5, softcap=softcap, mesh=mesh
            )
        )(q, k, v, ps, valid, jnp.int32(window))
    for b in range(4):
        n = int(valid[b])
        np.testing.assert_allclose(ref[b, :n], got[b, :n], atol=1e-5, rtol=1e-5)


def test_ring_four_way(mesh_seq4):
    q, k, v, ps = _setup(T=128)
    T, H = q.shape[1], q.shape[3]
    valid = jnp.full((4,), T, jnp.int32)
    mask = make_attention_mask(ps, T, valid)
    ref = dot_product_attention(q, k, v, mask=mask, scale=H**-0.5)
    with compat_set_mesh(mesh_seq4):
        got = jax.jit(
            lambda *a: ring_attention(*a, scale=H**-0.5, mesh=mesh_seq4)
        )(q, k, v, ps, valid, jnp.int32(0))
    np.testing.assert_allclose(ref, got, atol=1e-5, rtol=1e-5)


def test_ring_gradients_match(mesh):
    q, k, v, ps = _setup()
    T, H = q.shape[1], q.shape[3]
    valid = jnp.asarray([64, 50, 64, 40], jnp.int32)
    wmask = jnp.arange(T)[None, :, None, None] < valid[:, None, None, None]
    mask = make_attention_mask(ps, T, valid)

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, mask=mask, scale=H**-0.5)
        return jnp.sum((o * wmask) ** 2)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, ps, valid, jnp.int32(0),
                           scale=H**-0.5, mesh=mesh)
        return jnp.sum((o * wmask) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    with compat_set_mesh(mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_trainer_context_parallel_matches_dense(mesh):
    """Same seed, same batch: context-parallel loss == regular loss."""
    cfg = get_model_config("llama-tiny")
    batch = next(synthetic_batches(cfg, 4, 32))
    losses = {}
    for cp in (False, True):
        t = Trainer(
            cfg,
            TrainConfig(warmup_steps=1, total_steps=10, context_parallel=cp),
            mesh=mesh,
        )
        state = t.init(jax.random.key(0))
        _, m = t.step(state, batch)
        losses[cp] = float(m["loss"])
    assert np.isfinite(losses[True])
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-3)


def test_ring_flash_path_matches_reference(mesh):
    """The flash-in-ring path (per-step Pallas kernel + lse merge,
    VERDICT r2 next-step 8) — forced on with interpret mode on CPU —
    must match the dense oracle including ragged valid lengths."""
    q, k, v, ps = _setup()
    T, H = q.shape[1], q.shape[3]
    valid = jnp.asarray([64, 50, 64, 40], jnp.int32)
    mask = make_attention_mask(ps, T, valid)
    ref = dot_product_attention(q, k, v, mask=mask, scale=H**-0.5)
    with compat_set_mesh(mesh):
        got = jax.jit(
            lambda *a: ring_attention(
                *a, scale=H**-0.5, mesh=mesh,
                use_flash=True, interpret=True,
            )
        )(q, k, v, ps, valid, jnp.int32(0))
    for b in range(4):
        n = int(valid[b])
        np.testing.assert_allclose(ref[b, :n], got[b, :n], atol=1e-5, rtol=1e-5)


def test_ring_flash_gradients_match(mesh):
    """Training goes through the flash-in-ring path: gradients must match
    the dense reference (lse cotangents through the kernel VJP)."""
    q, k, v, ps = _setup()
    T, H = q.shape[1], q.shape[3]
    valid = jnp.asarray([64, 50, 64, 40], jnp.int32)
    wmask = jnp.arange(T)[None, :, None, None] < valid[:, None, None, None]
    mask = make_attention_mask(ps, T, valid)

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, mask=mask, scale=H**-0.5)
        return jnp.sum((o * wmask) ** 2)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, ps, valid, jnp.int32(0),
                           scale=H**-0.5, mesh=mesh,
                           use_flash=True, interpret=True)
        return jnp.sum((o * wmask) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    with compat_set_mesh(mesh):
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)
