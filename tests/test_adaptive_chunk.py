"""Adaptive chunk scheduling (engine/batcher.py:_pick_chunk_blocks).

The decode chunk length is a per-dispatch scheduling decision under
``chunk_policy="adaptive"``: sized from the live slots' remaining-token
budgets and the speculation-acceptance EMA, quantized to a small bucket
ladder. These tests pin the two contracts the feature stands on:

* **Parity** — greedy output is byte-identical between the fixed-chunk
  and adaptive paths, across speculate on/off, paged/dense caches, a
  JSON-masked slot, and slots finishing mid-chunk. Chunk boundaries
  must never leak into content.
* **Utilization** — ``engine.chunk_utilization`` (useful blocks ÷
  dispatched blocks, exported via the metrics snapshot and the obs step
  ring) rises under the adaptive policy when slots finish at staggered
  times, because dispatches stop being sized to the straggler.
"""

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.obs import global_steps
from pilottai_tpu.utils.metrics import global_metrics

# (prompt, max_new_tokens, json_mode): staggered budgets so slots finish
# mid-chunk at different blocks; one slot decodes under the JSON grammar
# mask.
REQS = (
    (list(range(3, 8)), 6, False),
    (list(range(11, 20)), 15, False),
    (list(range(23, 36)), 9, True),
    (list(range(41, 48)), 2, False),
)


def _make_batcher(policy, *, paged, speculate, chunk=6, buckets=(3, 6)):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=96, cache_dtype=jnp.float32,
        chunk_size=chunk, chunk_policy=policy, chunk_buckets=buckets,
        paged=paged, page_size=16, speculate=speculate,
        prefix_cache=0, use_pallas=False,
    )


def _run_batch(policy, *, paged, speculate, reqs=REQS, chunk=6,
               buckets=(3, 6)):
    b = _make_batcher(
        policy, paged=paged, speculate=speculate, chunk=chunk,
        buckets=buckets,
    )
    # Submit everything BEFORE starting so admission grouping (and with
    # it any padding) is identical run to run.
    reqs_out = []
    for prompt, mnt, json_mode in reqs:
        req = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=mnt, json_mode=json_mode
        )
        b.submit(req)
        reqs_out.append(req)
    b.start()
    try:
        outs = [r.future.result(timeout=600) for r in reqs_out]
    finally:
        b.stop()
    return outs


@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 2), (True, 0), (True, 2)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
def test_adaptive_matches_fixed_greedy(paged, speculate):
    fixed = _run_batch("fixed", paged=paged, speculate=speculate)
    adaptive = _run_batch("adaptive", paged=paged, speculate=speculate)
    assert fixed == adaptive, (
        f"adaptive chunking changed greedy output (paged={paged}, "
        f"speculate={speculate})"
    )
    # Non-vacuous: every request produced tokens, and the staggered
    # budgets actually finished slots at different times.
    assert all(len(o) >= 1 for o in fixed)
    if paged:
        # A slot that finished mid-chunk returned its pages at fold
        # time, ahead of the admission wave (per-slot early release).
        assert global_metrics.get("engine.early_page_releases") > 0


def _utilization_delta(policy, buckets):
    d0 = global_metrics.get("engine.blocks_dispatched")
    u0 = global_metrics.get("engine.blocks_useful")
    # Half the slots (budget 1 decode token) finish in the first block;
    # the other half run 5 blocks.
    reqs = (
        (list(range(3, 8)), 2, False),
        (list(range(11, 17)), 2, False),
        (list(range(23, 30)), 6, False),
        (list(range(41, 49)), 6, False),
    )
    _run_batch(policy, paged=False, speculate=0, reqs=reqs, chunk=8,
               buckets=buckets)
    disp = global_metrics.get("engine.blocks_dispatched") - d0
    useful = global_metrics.get("engine.blocks_useful") - u0
    assert disp > 0
    return useful / disp


def test_chunk_utilization_rises_with_adaptive_policy():
    fixed = _utilization_delta("fixed", (8,))
    adaptive = _utilization_delta("adaptive", (2, 4, 8))
    assert 0.0 < fixed <= 1.0 and 0.0 < adaptive <= 1.0
    assert adaptive > fixed, (
        f"adaptive utilization {adaptive:.3f} should beat fixed "
        f"{fixed:.3f} when half the slots finish early"
    )
    # Exported surfaces: the cumulative gauge in the metrics snapshot
    # and per-dispatch chunk size + utilization in the obs step ring.
    snap = global_metrics.snapshot()
    assert 0.0 < snap["gauges"]["engine.chunk_utilization"] <= 1.0
    chunks = [
        r for r in global_steps.snapshot() if r.get("kind") == "engine.chunk"
    ]
    assert chunks, "no engine.chunk records in the step ring"
    assert {"chunk_blocks", "blocks_useful", "utilization"} <= set(
        chunks[-1]
    )
    # decode_steps counts EXECUTED block-steps at fold time, not
    # dispatched chunk lengths: it can never exceed delivered tokens
    # (a useful block implies ≥1 accepted token).
    assert global_metrics.get("engine.decode_steps") <= global_metrics.get(
        "engine.generated_tokens_device"
    ) + global_metrics.get("engine.generated_tokens")
