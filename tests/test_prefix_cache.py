"""Automatic prefix caching (engine/prefix_cache.py + admit_group_prefix).

The safety invariant mirrors speculation's: a cache hit changes WHERE
prompt K/V comes from, never what gets generated — greedy output after a
hit must be bit-identical to a cold engine's. (Round-3 perf item: the
8B admission prefill measured as the dominant share of the agent-step
wave on v5e.)
"""

import asyncio

import numpy as np
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.prefix_cache import PrefixStore
from pilottai_tpu.engine.types import ChatMessage, GenerationParams
from pilottai_tpu.utils.metrics import global_metrics


def test_store_match_and_lru():
    s = PrefixStore(capacity=2, min_len=4, max_len=64)
    a = tuple(range(10, 30))
    b = tuple(range(40, 56))
    s.store(a, "ka", "va", 32)
    s.store(b, "kb", "vb", 16)
    # Proper-prefix match only, longest wins.
    assert s.match(list(a) + [1, 2]).ids == a
    assert s.match(list(a)[:8]) is None or len(s.match(list(a)[:8]).ids) <= 8
    assert s.match(list(b)) is None  # exact length: no tail left
    # LRU: touching a then inserting evicts b.
    s.match(list(a) + [1])
    s.store(tuple(range(70, 90)), "kc", "vc", 32)
    assert s.has(a) and not s.has(b)


def test_store_lcp_candidates():
    s = PrefixStore(capacity=4, min_len=4, max_len=64)
    base = tuple(range(100, 120))
    s.store(base + (1, 2, 3), "k", "v", 32)
    # A different continuation shares the 20-token base.
    cands = s.lcp_candidates(base + (7, 8, 9))
    assert cands == [len(base)]


async def _engine(prefix_cache, speculate=0, model="llama-tiny"):
    h = LLMHandler(LLMConfig(
        model_name=model, provider="cpu", engine_slots=4,
        engine_max_seq=256, engine_chunk=4, dtype="float32",
        engine_prefix_cache=prefix_cache, engine_speculate=speculate,
    ))
    await h.start()
    return h


# Long enough to clear the 64-token min_bucket entry floor.
LONG = ("You are the orchestrator. Analyze the task and respond with "
        "strict JSON as instructed by the rules preamble. Task: ")


@pytest.mark.asyncio
@pytest.mark.parametrize("model", ["llama-tiny", "gemma-tiny"])
async def test_hit_output_identical_to_cold_engine(model):
    """gemma-tiny exercises admit_group_prefix's sliding-window branch
    (the per-layer windowed tail attention) — llama never enters it."""
    params = GenerationParams(max_new_tokens=12, temperature=0.0)
    prompt = LONG + "summarize the report"

    cold = await _engine(prefix_cache=0, model=model)
    try:
        want = (await cold.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
    finally:
        await cold.stop()

    warm = await _engine(prefix_cache=8, model=model)
    try:
        h0 = global_metrics.get("engine.prefix_hits")
        first = (await warm.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
        again = (await warm.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
        hits = global_metrics.get("engine.prefix_hits") - h0
        assert first == want          # miss path unchanged
        assert again == want          # exact-repeat hit, same bits
        assert hits >= 1, "second request did not hit the prefix cache"
    finally:
        await warm.stop()


def test_prefix_extension_hit_identical():
    """A prompt extending a cached one (raw ids — the multi-turn /
    growing-transcript shape) admits via tail-prefill with output
    identical to a cold batcher. (Engine-level prompts end with the
    assistant marker, so THEIR sharing goes through the LCP entries —
    tested below.)"""
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
    from pilottai_tpu.models.common import init_params
    from pilottai_tpu.models.registry import get_model_config

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    base = [(i % 90) + 5 for i in range(80)]
    longer = base + [7, 9, 11, 13, 9, 7]

    def run(prefix_cache, prompts):
        b = ContinuousBatcher(
            cfg, params, n_slots=2, max_seq_len=256,
            cache_dtype=jnp.float32, chunk_size=4,
            prefix_cache=prefix_cache,
        )
        b.start()
        try:
            outs = []
            for p in prompts:
                req = GenRequest(prompt_ids=list(p), max_new_tokens=10)
                outs.append(b.submit(req).result(timeout=120))
            return outs, (
                len(b.prefix_store) if b.prefix_store else 0
            )
        finally:
            b.stop()

    (want,), _ = run(0, [longer])
    h0 = global_metrics.get("engine.prefix_hits")
    (_, got), entries = run(8, [base, longer])
    assert entries >= 1
    assert global_metrics.get("engine.prefix_hits") > h0
    assert got == want


def test_oversized_hit_falls_back_to_full_prefill():
    """When prefix_len + tail bucket exceeds max_seq, the dus tail write
    would CLAMP and shift K/V onto the cached prefix rows (review
    finding: silent corruption) — the hit must be rejected and the output
    must match a cold batcher's."""
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
    from pilottai_tpu.models.common import init_params
    from pilottai_tpu.models.registry import get_model_config

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    base = [(i % 90) + 5 for i in range(80)]
    big = base + [(i % 50) + 7 for i in range(38)]  # 118 ids, tail 39

    def run(prefix_cache, prompts):
        b = ContinuousBatcher(
            cfg, params, n_slots=2, max_seq_len=128,
            cache_dtype=jnp.float32, chunk_size=4,
            prefix_cache=prefix_cache,
        )
        b.start()
        try:
            return [
                b.submit(
                    GenRequest(prompt_ids=list(p), max_new_tokens=6)
                ).result(timeout=120)
                for p in prompts
            ]
        finally:
            b.stop()

    want = run(0, [big])[0]
    got = run(8, [base, big])[1]  # base seeds the store; big must miss
    assert got == want


@pytest.mark.asyncio
async def test_lcp_entry_serves_shared_preamble():
    """Two different tasks sharing the preamble: the derived LCP entry
    must make the THIRD distinct prompt hit without any full repeat."""
    params = GenerationParams(max_new_tokens=8, temperature=0.0)
    warm = await _engine(prefix_cache=8)
    try:
        await warm.generate_response(
            [ChatMessage(content=LONG + "first task")], params=params)
        await warm.generate_response(
            [ChatMessage(content=LONG + "second very different task")],
            params=params)
        h0 = global_metrics.get("engine.prefix_hits")
        await warm.generate_response(
            [ChatMessage(content=LONG + "third unseen task")],
            params=params)
        assert global_metrics.get("engine.prefix_hits") > h0, (
            "shared-preamble LCP entry never formed"
        )
    finally:
        await warm.stop()


@pytest.mark.asyncio
async def test_prefix_cache_with_speculation():
    """Both round-3 perf features together: hit + speculative decode
    still bit-match the cold engine's greedy output."""
    params = GenerationParams(max_new_tokens=16, temperature=0.0)
    prompt = LONG + "repeat repeat repeat repeat"

    cold = await _engine(prefix_cache=0, speculate=0)
    try:
        want = (await cold.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
    finally:
        await cold.stop()

    warm = await _engine(prefix_cache=8, speculate=4)
    try:
        for _ in range(3):
            got = (await warm.generate_response(
                [ChatMessage(content=prompt)], params=params)).content
            assert got == want
    finally:
        await warm.stop()


@pytest.mark.asyncio
async def test_prefix_cache_on_mesh():
    """Hit path under sharded params (the v5e-8 serving configuration):
    parity with the same engine's own miss output."""
    params = GenerationParams(max_new_tokens=8, temperature=0.0)
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=4,
        engine_max_seq=256, engine_chunk=4, dtype="float32",
        mesh_shape={"model": 2, "data": 2}, engine_prefix_cache=8,
    ))
    await h.start()
    try:
        prompt = LONG + "mesh parity"
        first = (await h.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
        h0 = global_metrics.get("engine.prefix_hits")
        again = (await h.generate_response(
            [ChatMessage(content=prompt)], params=params)).content
        assert global_metrics.get("engine.prefix_hits") > h0
        assert again == first
    finally:
        await h.stop()
