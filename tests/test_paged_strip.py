"""Multi-page strip parity for the paged-attention kernel
(ops/pallas/paged_attention.py, VERDICT r5 next-step 1).

The strip kernel visits pages in the same order and runs byte-identical
per-page math as the single-page grid (``n_strip=1`` — the pre-strip
kernel); regrouping pages into strips only changes how many a grid cell
covers. These tests pin that claim bit-for-bit across page sizes, strip
widths, int8-quantized pools, sliding windows, ragged slot lengths, and
the unallocated-page / partial-final-page edge cells — plus the
fused-ring variant against the separate ring-pass + merge it replaces.

``n_strip=1`` itself stays pinned against the dense gather oracle by
tests/test_paged.py, so the chain is strip == single-page == dense.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.decode import (
    _combine_stats,
    _prefix_stats_dense,
    _ring_stats,
)
from pilottai_tpu.ops.kvcache import quantize_kv
from pilottai_tpu.ops.paged import PageAllocator, gather_pages
from pilottai_tpu.ops.pallas.paged_attention import (
    paged_decode_attention,
    strip_vmem_bytes,
)

B, K, H = 4, 2, 64
MAX_PAGES = 4


def _ragged_lengths(P):
    """One of each edge case: partial final page, exactly one full page
    (page slots 1..3 unallocated), empty slot (whole table sentinel),
    one-past-a-page-boundary partial."""
    return (2 * P + P // 2 + 3, P, 0, 3 * P + 1)


def _mk_pool(rng, P, quantized=False):
    lengths = _ragged_lengths(P)
    num_pages = B * MAX_PAGES + 1
    alloc = PageAllocator(num_pages, P, B, max_pages_per_slot=MAX_PAGES)
    k_pool = np.zeros((K, num_pages, P, H), np.float32)
    v_pool = np.zeros((K, num_pages, P, H), np.float32)
    for b, ln in enumerate(lengths):
        if ln == 0:
            continue
        assert alloc.allocate(b, ln)
        for j in range(alloc.pages_needed(ln)):
            pg = alloc.table[b, j]
            k_pool[:, pg] = rng.normal(size=(K, P, H))
            v_pool[:, pg] = rng.normal(size=(K, P, H))
    k_pool = jnp.asarray(k_pool)
    v_pool = jnp.asarray(v_pool)
    scales = None
    if quantized:
        k_pool, ksc = quantize_kv(k_pool)
        v_pool, vsc = quantize_kv(v_pool)
        scales = (ksc, vsc)
    return (
        k_pool, v_pool, scales, jnp.asarray(alloc.table),
        jnp.asarray(lengths, jnp.int32),
    )


def _run(q, pool, n_strip, window=0, softcap=0.0, q_blocks=1, **kw):
    k_pool, v_pool, scales, table, lengths = pool
    return paged_decode_attention(
        q, k_pool, v_pool, table, lengths - 1, q_positions=lengths,
        n_blocks=MAX_PAGES, scale=H ** -0.5, softcap=softcap,
        window=window, q_blocks=q_blocks, n_strip=n_strip,
        k_scales=None if scales is None else scales[0],
        v_scales=None if scales is None else scales[1],
        interpret=True, **kw,
    )


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("window_frac", [0, 1])
def test_strip_matches_single_page_bitwise(quantized, window_frac):
    """Every strip width returns BYTE-identical (acc, m, l) to the
    single-page grid — including strip 3 (n_blocks=4 is not a multiple:
    the padded final cell must contribute nothing)."""
    P = 64
    rng = np.random.default_rng(0)
    pool = _mk_pool(rng, P, quantized=quantized)
    q = jnp.asarray(rng.normal(size=(B, 4, H)), jnp.float32)
    window = (P + P // 2 + 5) * window_frac
    base = _run(q, pool, n_strip=1, window=window, softcap=30.0)
    for strip in (2, 3, 4, 8):
        got = _run(q, pool, n_strip=strip, window=window, softcap=30.0)
        for name, a, b in zip("acc m l".split(), base, got):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"P={P} strip={strip} stat={name}",
            )


@pytest.mark.parametrize("P", [128, 256])
def test_strip_large_pages_bitwise(P):
    """The serving page sizes (128 and 256), one representative config
    each — int8 pool + sliding window, the full-feature cell — so the
    {64, 128, 256} page-size axis stays covered without the full
    cross-product's interpret-mode cost (that runs at P=64 above)."""
    rng = np.random.default_rng(6)
    pool = _mk_pool(rng, P, quantized=True)
    q = jnp.asarray(rng.normal(size=(B, 4, H)), jnp.float32)
    base = _run(q, pool, n_strip=1, window=P + P // 2 + 5, softcap=30.0)
    for strip in (2, 3, 8):
        got = _run(q, pool, n_strip=strip, window=P + P // 2 + 5,
                   softcap=30.0)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("P", [64, 128])
def test_strip_q_blocks_matches_single_page_bitwise(P):
    """The speculative shape (D packed queries per head row, per-row
    window offsets) under strips == the single-page grid, bit for bit."""
    rng = np.random.default_rng(1)
    D, G = 3, 2
    pool = _mk_pool(rng, P)
    q = jnp.asarray(rng.normal(size=(B, K * G * D, H)), jnp.float32)
    base = _run(q, pool, n_strip=1, window=P + 7, q_blocks=D)
    for strip in (2, 4):
        got = _run(q, pool, n_strip=strip, window=P + 7, q_blocks=D)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_strip_empty_and_unallocated_rows_stay_empty():
    """The length-0 slot (whole table sentinel) and slots whose table has
    sentinel page slots past their allocation must produce l == 0 /
    untouched stats exactly like the single-page kernel."""
    rng = np.random.default_rng(2)
    pool = _mk_pool(rng, 64)
    lengths = np.asarray(pool[4])
    q = jnp.asarray(rng.normal(size=(B, 4, H)), jnp.float32)
    _, _, l = _run(q, pool, n_strip=4)
    assert float(np.asarray(l)[lengths == 0].max(initial=0.0)) == 0.0
    # Live rows match the dense oracle (strip == single page == dense).
    acc, m, l = _run(q, pool, n_strip=4)
    k_pool, v_pool, _, table, lens = pool
    acc_r, m_r, l_r = _prefix_stats_dense(
        q.reshape(B, K, 2, H),
        gather_pages(k_pool, table, MAX_PAGES),
        gather_pages(v_pool, table, MAX_PAGES),
        lens - 1, lens, H ** -0.5, 0.0, 0,
    )
    live = lengths > 0
    np.testing.assert_allclose(
        np.asarray(acc)[live], np.asarray(acc_r)[live],
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(l)[live], np.asarray(l_r)[live], rtol=1e-5
    )


@pytest.mark.parametrize("window", [0, 40])
@pytest.mark.parametrize("step", [0, 3, 7])
def test_fused_ring_matches_separate_merge(window, step):
    """The fused in-chunk ring (final grid cell) must reproduce the
    separate ring pass + ``_merge_stats`` combine that the plain decode
    chunk used to dispatch per layer — the exact contract
    ``engine/decode.py`` now relies on."""
    rng = np.random.default_rng(3)
    pool = _mk_pool(rng, 64)
    G = 2
    q = jnp.asarray(rng.normal(size=(B, K * G, H)), jnp.float32)
    R = 8
    rk = jnp.asarray(rng.normal(size=(B, K, R, H)), jnp.float32)
    rv = jnp.asarray(rng.normal(size=(B, K, R, H)), jnp.float32)
    for strip in (1, 2, 4):
        acc, m, l = _run(
            q, pool, n_strip=strip,
            window=window, ring_k=rk, ring_v=rv,
            ring_step=jnp.int32(step),
        )
        fused = np.asarray(acc) / np.maximum(np.asarray(l), 1e-30)[..., None]
        acc_p, m_p, l_p = _run(q, pool, n_strip=strip, window=window)
        acc_c, m_c, l_c = _ring_stats(
            q.reshape(B, K, G, H), rk, rv, jnp.int32(step),
            H ** -0.5, 0.0, window,
        )
        ref = np.asarray(
            _combine_stats(acc_p, m_p, l_p, acc_c, m_c, l_c)
        )
        np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-6)


def test_fused_ring_identical_across_strips():
    """Strip width must not change the fused result at all (pages merge
    before the ring in every variant)."""
    rng = np.random.default_rng(4)
    pool = _mk_pool(rng, 64, quantized=True)
    q = jnp.asarray(rng.normal(size=(B, 4, H)), jnp.float32)
    R = 6
    rk = jnp.asarray(rng.normal(size=(B, K, R, H)), jnp.float32)
    rv = jnp.asarray(rng.normal(size=(B, K, R, H)), jnp.float32)
    kw = dict(ring_k=rk, ring_v=rv, ring_step=jnp.int32(2))
    base = _run(q, pool, n_strip=1, **kw)
    for strip in (2, 4):
        got = _run(q, pool, n_strip=strip, **kw)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_strip_wider_than_blocks_clamps():
    """A strip wider than the visit count clamps instead of reading
    garbage (the batcher may autotune 8 on a 4-page bound)."""
    rng = np.random.default_rng(5)
    pool = _mk_pool(rng, 64)
    q = jnp.asarray(rng.normal(size=(B, 4, H)), jnp.float32)
    base = _run(q, pool, n_strip=1)
    got = _run(q, pool, n_strip=16)
    for a, b in zip(base, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_decode_chunk_matches_xla_fallback(monkeypatch):
    """End-to-end wiring of the fused path through ``decode_chunk``:
    paged + Pallas (kernel routed through interpret mode, strip 2,
    ring_step threaded from the while_loop carry) must emit the same
    greedy tokens as the XLA gather fallback — the cross-backend pin
    the engine's long-context path rests on."""
    import functools

    import jax

    import pilottai_tpu.engine.decode as dec
    from pilottai_tpu.engine.decode import (
        DecodeState,
        admit_group,
        decode_chunk,
        pack_admit_meta,
    )
    from pilottai_tpu.engine.sampling import SamplingState
    from pilottai_tpu.models.common import init_params
    from pilottai_tpu.models.registry import get_model_config
    from pilottai_tpu.ops.paged import PagedKVCache

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    Bs, S, A, T, P = 4, 128, 4, 64, 32
    rng = np.random.default_rng(0)
    lens = np.array([17, 33, 0, 0], np.int32)
    tokens = np.zeros((A, T), np.int32)
    for i in range(2):
        tokens[i, : lens[i]] = rng.integers(2, cfg.vocab_size, lens[i])
    mi, mf = pack_admit_meta(
        A, slots=[0, 2, Bs, Bs], seeds=range(10, 10 + A),
        budgets=[12, 12, 0, 0], lens=lens, pad_slot=Bs,
    )
    base_args = (jnp.asarray(tokens), jnp.asarray(mi), jnp.asarray(mf))

    def admit():
        alloc = PageAllocator(4 * Bs + 1, P, Bs, max_pages_per_slot=S // P)
        for row, slot in enumerate([0, 2]):
            assert alloc.allocate(slot, int(lens[row]) + 13)
        pr = np.full((A, S // P), alloc.sentinel, np.int32)
        pr[0] = alloc.table[0]
        pr[1] = alloc.table[2]
        cache = PagedKVCache.create(
            cfg.n_layers, Bs, 4 * Bs + 1, P, cfg.n_kv_heads, cfg.head_dim,
            dtype=jnp.float32,
        )
        out = admit_group(
            params, cfg, cache, DecodeState.create(Bs),
            SamplingState.create(Bs), *base_args, use_flash=False,
            page_rows=jnp.asarray(pr),
        )
        return out, jnp.asarray(alloc.table)

    (c, d, s, first_a, _), table = admit()
    ref = []
    for _ in range(2):
        t_, v_, c, d, s = decode_chunk(
            params, cfg, c, d, s, 8, use_pallas=False, table=table
        )
        ref.append((np.asarray(t_), np.asarray(v_)))

    monkeypatch.setattr(
        dec, "paged_decode_attention",
        functools.partial(dec.paged_decode_attention, interpret=True),
    )
    (c, d, s, first_b, _), table = admit()
    np.testing.assert_array_equal(np.asarray(first_a), np.asarray(first_b))
    for i in range(2):
        t_, v_, c, d, s = decode_chunk(
            params, cfg, c, d, s, 8, use_pallas=True, table=table,
            page_strip=2,
        )
        np.testing.assert_array_equal(ref[i][1], np.asarray(v_))
        np.testing.assert_array_equal(ref[i][0], np.asarray(t_))


def test_strip_vmem_estimate_monotone():
    """The autotuner's VMEM guard: estimates grow with strip width and
    count the scale planes only when quantized."""
    a = strip_vmem_bytes(2, 128, 8, 128, 2, False)
    b = strip_vmem_bytes(4, 128, 8, 128, 2, False)
    c = strip_vmem_bytes(4, 128, 8, 128, 1, True)
    assert b == 2 * a
    assert c > strip_vmem_bytes(4, 128, 8, 128, 1, False)
