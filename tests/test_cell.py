"""Serving cell (distributed/cell.py + router.py, ISSUE 11).

Contracts pinned here:

* the radix routing table returns the replica holding the LONGEST LIVE
  prefix, decays on replica-side KV eviction (``HostTier.on_evict``)
  and never surfaces a dead/draining replica's entry over a live one;
* the router never sends new work to a draining / watchdog-stalled /
  breaker-open replica, prefers SLO headroom, and sheds per class at
  the cell boundary (batch first, interactive last);
* cross-replica session migration moves KV in the host tier's transfer
  format and greedy output is byte-identical across a mid-session
  migration AND a full replica drain (the tier parity contract,
  extended across replicas);
* the cell's /healthz and /slo.json aggregate across replicas;
* a replica killed mid-soak re-routes everything (cell-level
  recovered_frac == 1.0) with interactive attainment above the
  degraded floor (chaos lane).
"""

import asyncio
import json
import re
import time

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.distributed import (
    CellOverloaded,
    CellReplica,
    ReplicaRouter,
    ReplicaSignals,
    RoutingTable,
    ServingCell,
    route_key,
    session_kv_from_wire,
    session_kv_to_wire,
)
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.kvcache import HostTier
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.reliability import EngineOverloaded, global_engine_health
from pilottai_tpu.utils.metrics import global_metrics

import numpy as np


# --------------------------------------------------------------------- #
# Routing table
# --------------------------------------------------------------------- #

def test_routing_table_longest_live_prefix():
    t = RoutingTable()
    base = tuple(range(50, 90))
    t.note(base[:20], "shallow")
    t.note(base[:35], "deep")
    query = base + (1, 2, 3)
    # Deepest entry wins when its owner is live...
    assert t.lookup(query) == ("deep", 35)
    # ...but a dead owner's deeper entry must NOT shadow the live
    # shallower one (the satellite's acceptance case).
    assert t.lookup(query, alive=["shallow"]) == ("shallow", 20)
    assert t.lookup(query, alive=["nobody"]) == (None, 0)
    # forget_replica drops everything the replica owned.
    assert t.forget_replica("deep") == 1
    assert t.lookup(query) == ("shallow", 20)


def test_routing_table_lru_capacity_and_forget():
    t = RoutingTable(capacity=2)
    t.note((1, 2, 3), "a")
    t.note((4, 5, 6), "b")
    t.note((7, 8, 9), "c")  # evicts (1,2,3) — oldest
    assert t.lookup((1, 2, 3, 0)) == (None, 0)
    assert t.lookup((4, 5, 6, 0)) == ("b", 3)
    t.forget((4, 5, 6))
    assert t.lookup((4, 5, 6, 0)) == (None, 0)
    assert len(t) == 1


def test_routing_table_forget_owned_checks_ownership():
    """Replica A evicting its copy of a shared preamble must not decay
    an entry pointing at replica B, whose KV is still live — the cell
    wires the per-replica eviction hook through forget_owned."""
    t = RoutingTable()
    key = tuple(range(20))
    t.note(key, "b")
    t.forget_owned(key, "a")          # not the owner: no-op
    assert t.lookup(key + (1,)) == ("b", 20)
    t.forget_owned(key, "b")
    assert t.lookup(key + (1,)) == (None, 0)


def test_routing_table_decays_on_host_tier_eviction():
    """Replica-side KV eviction decays the cell's affinity entry: the
    host tier's ``on_evict`` (fired when a budget eviction drops an
    entry from BOTH tiers) is wired straight to ``RoutingTable.forget``
    — affinity must not outlive the KV it points at."""
    table = RoutingTable()
    # One panel pair = 2 x (2*4*8) float32 = 512 bytes; budget holds one
    # entry but not two, so the second put evicts the first.
    tier = HostTier(budget_bytes=600)
    tier.on_evict = table.forget

    def panel(seed):
        rng = np.random.RandomState(seed)
        return (rng.randn(2, 4, 8).astype(np.float32),
                rng.randn(2, 4, 8).astype(np.float32))

    key_a = tuple(range(100, 116))
    key_b = tuple(range(300, 316))
    table.note(key_a, "r0")
    assert tier.put(key_a, panel(0), tokens=16, rows=16)
    assert table.lookup(key_a + (1,)) == ("r0", 16)
    # Second entry overflows the budget; A (colder) is evicted and the
    # callback must decay the routing entry.
    assert tier.put(key_b, panel(1), tokens=16, rows=16)
    assert table.lookup(key_a + (1,)) == (None, 0)


# --------------------------------------------------------------------- #
# Router policy
# --------------------------------------------------------------------- #

def _sig(rid, **kw):
    return ReplicaSignals(replica_id=rid, **kw)


def test_router_never_routes_to_unroutable_replicas():
    r = ReplicaRouter()
    sigs = [
        _sig("ok"),
        _sig("draining", draining=True),
        _sig("stalled", healthy=False),
        _sig("tripped", breaker_open=True),
    ]
    for _ in range(8):
        rid, _ = r.pick((1, 2, 3), sigs)
        assert rid == "ok"
    # A pinned session whose owner is draining re-routes too.
    rid, _ = r.pick((1, 2, 3), sigs, pinned="draining")
    assert rid == "ok"
    with pytest.raises(CellOverloaded):
        r.pick((1,), [s for s in sigs if s.replica_id != "ok"])


def test_router_prefers_slo_headroom_and_affinity():
    r = ReplicaRouter()
    key = tuple(range(40))
    # Same queue state; b is burning its interactive budget 5x.
    sigs = [
        _sig("a", burn_rate={"interactive": 0.0}),
        _sig("b", burn_rate={"interactive": 5.0}),
    ]
    picks = {r.pick(key, sigs, slo_class="interactive")[0]
             for _ in range(6)}
    assert picks == {"a"}
    # Affinity overcomes a modest load gap: b holds the whole prefix.
    r.table.note(key, "b")
    sigs = [
        _sig("a", queue_frac=0.0),
        _sig("b", queue_frac=0.3),
    ]
    rid, lcp = r.pick(key, sigs)
    assert rid == "b" and lcp == len(key)


def test_router_sheds_per_class_at_cell_boundary():
    r = ReplicaRouter(batch_shed_frac=0.75)
    # All replicas past the batch threshold but below full: batch sheds,
    # interactive still routes.
    sigs = [_sig("a", queue_frac=0.8), _sig("b", queue_frac=0.9)]
    rid, _ = r.pick((1, 2), sigs, slo_class="interactive")
    assert rid in ("a", "b")
    with pytest.raises(CellOverloaded):
        r.pick((1, 2), sigs, slo_class="batch")
    # Degraded-to-shed-batch rung sheds batch even with queue room.
    sigs = [_sig("a", degrade_level=4)]
    with pytest.raises(CellOverloaded):
        r.pick((1, 2), sigs, slo_class="batch")
    rid, _ = r.pick((1, 2), sigs, slo_class="interactive")
    assert rid == "a"
    # Full queues ground interactive too.
    sigs = [_sig("a", queue_frac=1.0), _sig("b", queue_frac=1.2)]
    with pytest.raises(CellOverloaded):
        r.pick((1, 2), sigs, slo_class="interactive")


# --------------------------------------------------------------------- #
# Cell over mock replicas
# --------------------------------------------------------------------- #

def _mock_cell(n=3, latency=0.0, soft_inflight=None):
    reps = []
    for i in range(n):
        h = LLMHandler(LLMConfig(provider="mock"))
        if latency:
            h.backend.latency = latency
        reps.append(CellReplica(f"r{i}", h, soft_inflight=soft_inflight))
    return ServingCell(reps)


@pytest.mark.asyncio
async def test_cell_session_pin_and_affinity_counters():
    cell = _mock_cell()
    await cell.start()
    try:
        look0 = global_metrics.get("cell.affinity_lookups")
        hits0 = global_metrics.get("cell.affinity_hits")
        await cell.apredict("please analyze the fleet report",
                            session_id="sess-1")
        owner = cell.sessions["sess-1"]
        for _ in range(3):
            await cell.apredict("please analyze the fleet report, more",
                                session_id="sess-1")
            assert cell.sessions["sess-1"] == owner  # sticky
        assert global_metrics.get("cell.affinity_lookups") - look0 == 4
        assert global_metrics.get("cell.affinity_hits") - hits0 >= 3
        # Routed counters land in the request's class.
        routed0 = global_metrics.get("cell.routed.batch")
        await cell.apredict("bulk job", slo_class="batch")
        assert global_metrics.get("cell.routed.batch") - routed0 == 1
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_cell_sheds_when_replicas_saturate():
    # soft_inflight=1 → a replica with one in-flight call reads
    # queue_frac 1.0; with every replica busy, the next interactive
    # request sheds AT THE CELL (EngineOverloaded → HTTP 429) and the
    # per-class counter moves.
    cell = _mock_cell(n=2, latency=0.3, soft_inflight=1)
    await cell.start()
    try:
        shed0 = global_metrics.get("cell.shed.interactive")
        first = [
            asyncio.create_task(cell.apredict(f"task {i}"))
            for i in range(2)
        ]
        await asyncio.sleep(0.05)  # both in flight
        with pytest.raises(EngineOverloaded):
            await cell.apredict("one too many")
        assert global_metrics.get("cell.shed.interactive") - shed0 == 1
        await asyncio.gather(*first)
        # Capacity back: routes again.
        assert await cell.apredict("after the wave")
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_cell_batch_sheds_before_interactive():
    cell = _mock_cell(n=2, latency=0.3, soft_inflight=4)
    await cell.start()
    try:
        # 3 in flight per soft limit 4 → queue_frac 0.75: past the batch
        # threshold, below interactive's.
        first = [
            asyncio.create_task(cell.apredict(f"task {i}"))
            for i in range(6)
        ]
        await asyncio.sleep(0.05)
        with pytest.raises(EngineOverloaded):
            await cell.apredict("bulk", slo_class="batch")
        out = await cell.apredict("interactive squeezes in")
        assert out
        await asyncio.gather(*first)
    finally:
        await cell.stop()


def test_stale_completion_does_not_undo_migration_pin():
    """A request that was in flight on the OLD owner when the session
    migrated must not re-pin the session on completion — the newer live
    pin owns the KV. A dead/draining current pin still yields
    (failover re-pins normally)."""
    cell = _mock_cell(n=3)
    rids = list(cell.replicas)
    key = route_key("some session prompt")
    cell.sessions["s"] = rids[1]          # migration moved it to r1
    cell._after_success(rids[0], key, "s")  # stale completion on r0
    assert cell.sessions["s"] == rids[1]
    # Draining target never takes a pin.
    cell.replicas[rids[2]].draining = True
    cell._after_success(rids[2], key, "s2")
    assert "s2" not in cell.sessions
    # Failover: the current pin is draining, the new server takes over.
    cell.replicas[rids[1]].draining = True
    cell._after_success(rids[0], key, "s")
    assert cell.sessions["s"] == rids[0]


def test_idle_cell_slo_aggregate_boots_clean():
    """No traffic = no misses: a fresh cell's aggregate must read
    attainment 1.0 / burn 0.0 per class (the single-engine surface's
    boot behavior), never an alarming zero-filled aggregate."""
    cell = _mock_cell(n=2)
    snap = cell.slo_snapshot()
    for cls, entry in snap["classes"].items():
        assert entry["requests"] == 0
        assert entry["attainment"] == 1.0, (cls, entry)
        assert entry["burn_rate"] == 0.0


@pytest.mark.asyncio
async def test_client_cancel_during_drain_propagates():
    """A client disconnect racing a drain must stay a cancellation —
    only tasks the DRAIN explicitly cancelled re-admit; an abandoned
    request is never resurrected on a sibling."""
    cell = _mock_cell(n=2, latency=0.5)
    await cell.start()
    try:
        outer = asyncio.create_task(cell.apredict("slow request"))
        await asyncio.sleep(0.05)
        busy = [rep for rep in cell.replicas.values() if rep.inflight]
        assert busy, "request never went in flight"
        busy[0].draining = True  # a drain has started on that replica
        outer.cancel()           # ... and the client walks away
        with pytest.raises(asyncio.CancelledError):
            await outer
        await asyncio.sleep(0.05)
        assert busy[0].inflight == 0
        # Nothing re-routed: the other replica saw no resurrected work.
        others = [r for r in cell.replicas.values() if r is not busy[0]]
        assert all(r.inflight == 0 for r in others)
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_cell_health_and_slo_aggregate_over_http():
    from pilottai_tpu.server import APIServer

    cell = _mock_cell(n=2)
    await cell.start()
    server = await APIServer(cell, host="127.0.0.1", port=0).start()

    async def get(path):
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port
        )
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        return status, json.loads(body)

    try:
        await cell.apredict("warm one request", session_id="s-http")
        status, body = await get("/healthz")
        assert status == 200 and body["routable"] == 2
        status, body = await get("/slo.json")
        assert body["aggregate"] is True
        assert "interactive" in body["classes"]
        assert set(body["replicas"]) == set(cell.replicas)
        assert body["classes"]["interactive"]["requests"] >= 1
        # One replica stalls (EngineHealth source): cell still 200 but
        # reports it; both stalled → 503.
        rids = list(cell.replicas)
        try:
            global_engine_health.mark_stalled(
                source=cell.replicas[rids[0]].health_source,
                reason="test stall", retry_after=1.0,
            )
            status, body = await get("/healthz")
            assert status == 200 and body["routable"] == 1
            assert body["stalled"] == [rids[0]]
            global_engine_health.mark_stalled(
                source=cell.replicas[rids[1]].health_source,
                reason="test stall", retry_after=1.0,
            )
            status, body = await get("/healthz")
            assert status == 503 and body["status"] == "unhealthy"
            # PR 8 contract: a grounded cell still hints when to retry.
            assert body["retry_after"] > 0
            with pytest.raises(EngineOverloaded):
                await cell.apredict("nowhere to go")
        finally:
            for rid in rids:
                global_engine_health.mark_recovered(
                    cell.replicas[rid].health_source
                )
    finally:
        await server.stop()
        await cell.stop()
        global_engine_health.reset()


@pytest.mark.asyncio
async def test_cell_export_completeness_clean():
    """Every cell.* series declared at obs import reaches the exported
    surface (PR 6 discipline) after real cell traffic."""
    from pilottai_tpu.obs import export_completeness

    cell = _mock_cell(n=2)
    await cell.start()
    try:
        await cell.apredict("drive some traffic", session_id="s-exp")
        problems = export_completeness()
        cell_problems = [p for p in problems if "cell." in str(p)]
        assert not cell_problems, cell_problems
    finally:
        await cell.stop()


# --------------------------------------------------------------------- #
# Chaos lane: replica killed mid-soak
# --------------------------------------------------------------------- #

@pytest.mark.chaos
@pytest.mark.asyncio
async def test_cell_replica_kill_mid_soak_recovers():
    """The CI cell chaos job's assertion (ISSUE 11 satellite): one
    replica dies mid-soak under open-loop traffic; every request still
    completes (cell-level recovered_frac == 1.0 — failures re-route,
    the health-tripped replica stops receiving new work) and the
    interactive aggregate attainment stays above the degraded floor."""
    cell = _mock_cell(n=3, latency=0.02)
    await cell.start()
    victim = next(iter(cell.replicas.values()))
    try:
        results = []

        async def one(i):
            try:
                out = await cell.apredict(
                    f"soak request {i}", session_id=f"soak-{i % 4}"
                )
                return "ok" if out else "error"
            except EngineOverloaded:
                return "shed"
            except Exception:  # noqa: BLE001 — the assertion target
                return "error"

        tasks = []
        for i in range(60):
            if i == 30:
                # Kill: the backend starts failing every call AND the
                # watchdog verdict trips — exactly what a wedged device
                # looks like to the cell.
                victim.handler.backend._fail_re = re.compile(".")
                global_engine_health.mark_stalled(
                    source=victim.health_source,
                    reason="chaos kill", retry_after=1.0,
                )
            tasks.append(asyncio.create_task(one(i)))
            await asyncio.sleep(0.005)
        results = await asyncio.gather(*tasks)
        completed = results.count("ok")
        errors = results.count("error")
        recovered_frac = completed / max(len(results) - results.count(
            "shed"), 1)
        assert recovered_frac == 1.0, (
            f"{errors} requests died with the replica (results: "
            f"{results})"
        )
        assert global_metrics.get("cell.rerouted") >= 0
        # No NEW work landed on the dead replica after the trip: its
        # signals exclude it from routing.
        assert not victim.signals().routable()
        snap = cell.slo_snapshot()
        attain = snap["classes"]["interactive"]["attainment"]
        # Degraded floor: the kill may miss the in-flight handful, never
        # the majority (target 0.99; floor 0.75 = incident mode).
        assert attain >= 0.75, f"interactive attainment collapsed: {attain}"
    finally:
        global_engine_health.reset()
        await cell.stop()


# --------------------------------------------------------------------- #
# Transfer format: wire round-trip
# --------------------------------------------------------------------- #

def test_session_kv_wire_roundtrip():
    from pilottai_tpu.engine.kvcache.index import KVCacheIndex

    src = KVCacheIndex(host_bytes=1 << 20)
    dst = KVCacheIndex(host_bytes=1 << 20)
    key = tuple(range(70, 140))
    rng = np.random.RandomState(3)
    ks = rng.randn(2, 2, 70, 4).astype(np.float32)
    vs = rng.randn(2, 2, 70, 4).astype(np.float32)
    assert src.host.put(key, (ks, vs), tokens=70, rows=70, kind="dense")
    src.host.note_session("sess-w", key + (7, 8))
    export = src.export_session("sess-w")
    assert export is not None and len(export["entries"]) == 1
    # Entries COPY (a shared preamble may serve other sessions; a
    # target-side budget reject must not lose the KV) — only the
    # session pin leaves the source.
    assert len(src.host) == 1
    assert src.host.lineage("sess-w") is None
    # JSON wire round-trip (the control-plane shape).
    wire = json.loads(json.dumps(session_kv_to_wire(export)))
    restored = session_kv_from_wire(wire)
    assert dst.import_session(restored) == {
        "accepted": 1, "tokens": 70, "rejected": 0,
    }
    entry = dst.host.get(key)
    assert entry is not None
    hk, hv = entry.copy.wait()
    np.testing.assert_array_equal(hk, ks)
    np.testing.assert_array_equal(hv, vs)
    assert dst.host.lineage("sess-w") == key + (7, 8)


# --------------------------------------------------------------------- #
# Engine-level: byte-identical migration and drain (cpu llama-tiny)
# --------------------------------------------------------------------- #

def _engine_cfg():
    return LLMConfig(
        model_name="llama-tiny", provider="cpu", dtype="float32",
        engine_slots=2, engine_max_seq=256, engine_chunk=8,
        engine_prefix_cache=1, engine_kvcache_host_mb=64,
    )


BASE = (
    "Session X memory: persona agent-7; "
    + "analyze the quarterly report and respond with JSON please. " * 2
)
TURN1 = BASE + "user: first step?"
GREEDY = dict(max_new_tokens=6, temperature=0.0)


async def _reference_turns():
    h = LLMHandler(_engine_cfg())
    await h.start()
    try:
        p = GenerationParams(**GREEDY)
        r1 = await h.apredict(TURN1, params=p, session_id="s")
        r2 = await h.apredict(
            TURN1 + r1 + " user: second step?", params=p, session_id="s"
        )
        return r1, r2
    finally:
        await h.stop()


@pytest.fixture(scope="module")
def reference_turns():
    return asyncio.run(_reference_turns())


@pytest.mark.asyncio
async def test_mid_session_migration_byte_identical(reference_turns):
    """Acceptance bar: a greedy session with a mid-session migration
    matches the unmigrated single-engine run byte for byte, and the KV
    really moved (export carried entries; the target RESTORED instead
    of re-prefilling)."""
    cell = ServingCell([LLMHandler(_engine_cfg()) for _ in range(2)])
    await cell.start()
    try:
        p = GenerationParams(**GREEDY)
        r1 = await cell.apredict(TURN1, params=p, session_id="s")
        src = cell.sessions["s"]
        restores0 = global_metrics.get("engine.kvcache.restores")
        report = await cell.migrate_session("s")
        assert report["from"] == src
        assert report["entries"] >= 1 and report["accepted"] >= 1
        assert report["tokens"] > len(TURN1) // 2
        r2 = await cell.apredict(
            TURN1 + r1 + " user: second step?", params=p, session_id="s"
        )
        assert cell.sessions["s"] == report["to"]
        assert (r1, r2) == reference_turns, (
            "mid-session migration changed greedy output"
        )
        assert global_metrics.get("engine.kvcache.restores") > restores0, (
            "turn 2 never restored the migrated KV on the target"
        )
        assert global_metrics.get("cell.migrations") >= 1
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_replica_drain_byte_identical(reference_turns):
    """Full drain: the pinned replica drains between turns — sessions
    (and their KV) migrate, the router stops sending it work, and the
    session's next turn elsewhere matches the unmigrated run."""
    cell = ServingCell([LLMHandler(_engine_cfg()) for _ in range(2)])
    await cell.start()
    try:
        p = GenerationParams(**GREEDY)
        r1 = await cell.apredict(TURN1, params=p, session_id="s")
        owner = cell.sessions["s"]
        report = await cell.drain(owner)
        assert report["migrated_sessions"] == 1
        assert not cell.replicas[owner].signals().routable()
        r2 = await cell.apredict(
            TURN1 + r1 + " user: second step?", params=p, session_id="s"
        )
        assert cell.sessions["s"] != owner
        assert (r1, r2) == reference_turns, (
            "drain + resume changed greedy output"
        )
        assert global_metrics.get("cell.drains") >= 1
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_drain_readmits_inflight_request(reference_turns):
    """An in-flight unary request on the draining replica past the
    grace window is cancelled and re-admitted on a sibling — the
    client sees one answer, byte-identical to an undrained run."""
    cell = ServingCell([LLMHandler(_engine_cfg()) for _ in range(2)])
    await cell.start()
    try:
        p = GenerationParams(max_new_tokens=24, temperature=0.0)
        # Undrained reference from THIS cell (replica weights are
        # identical, so any replica's greedy answer is THE answer).
        want = await cell.apredict(TURN1, params=p)
        inflight = asyncio.create_task(
            cell.apredict(TURN1, params=p)
        )
        await asyncio.sleep(0.05)  # let it route + admit
        routed_to = [
            rid for rid, rep in cell.replicas.items() if rep.inflight
        ]
        assert routed_to, "request never went in flight"
        report = await cell.drain(routed_to[0], grace_s=0.0)
        got = await inflight
        assert got == want, "drain re-admission changed output"
        assert report["readmitted"] >= 1
        assert global_metrics.get("cell.rerouted") >= 1
    finally:
        await cell.stop()


def test_paged_chain_migration_restores_on_target():
    """Paged-tier transfer at batcher level: a session's page chain
    exports from A (device gather → host panels), imports into B's
    cold tier, and B's resume RESTORES the chain (prefilling less than
    half the prompt) with greedy output matching a cold engine's."""
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
    from pilottai_tpu.models.common import init_params
    from pilottai_tpu.models.registry import get_model_config

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    def make(host_mb):
        return ContinuousBatcher(
            cfg, params, n_slots=2, max_seq_len=256,
            cache_dtype=jnp.float32, chunk_size=4, prefix_cache=4,
            kvcache_host_mb=host_mb, use_pallas=False, paged=True,
            page_size=16,
        )

    base = [(i % 90) + 5 for i in range(80)]
    resume = base + [7, 9, 11, 13]

    # Cold reference for the resume prompt.
    cold = make(host_mb=0)
    cold.start()
    try:
        want = cold.submit(
            GenRequest(prompt_ids=list(resume), max_new_tokens=6)
        ).result(timeout=600)
    finally:
        cold.stop()

    a = make(host_mb=64)
    b = make(host_mb=64)
    a.start()
    b.start()
    try:
        a.submit(GenRequest(
            prompt_ids=list(base), max_new_tokens=6, session_id="s-m",
        )).result(timeout=600)
        export = a.export_session_kv("s-m")
        assert export is not None and len(export["entries"]) >= 1
        assert all(e["kind"] == "page" for e in export["entries"])
        landed = b.import_session_kv(export)
        assert landed["accepted"] == len(export["entries"])
        assert landed["tokens"] > 0
        restores0 = global_metrics.get("engine.kvcache.restores")
        pf0 = global_metrics.get("engine.prefill_tokens")
        out = b.submit(GenRequest(
            prompt_ids=list(resume), max_new_tokens=6, session_id="s-m",
        )).result(timeout=600)
        prefilled = global_metrics.get("engine.prefill_tokens") - pf0
        assert out == want, "paged migration changed greedy output"
        assert global_metrics.get("engine.kvcache.restores") > restores0
        assert 0 < prefilled < len(resume) // 2, (
            f"target re-prefilled {prefilled}/{len(resume)} tokens"
        )
    finally:
        a.stop()
        b.stop()


# --------------------------------------------------------------------- #
# Degraded-mesh awareness (ISSUE 16): routing, rebalance runbook,
# corrupted migration frames
# --------------------------------------------------------------------- #

def test_router_down_scores_degraded_mesh_rung():
    """Same load, one replica on a survivor sub-mesh: fresh work routes
    to the intact sibling; a degraded replica is still a last resort
    (routable, never excluded — capacity at a worse rung beats a
    shed)."""
    r = ReplicaRouter()
    sigs = [_sig("full"), _sig("deg", mesh_rung=1)]
    assert {r.pick((1, 2, 3), sigs)[0] for _ in range(6)} == {"full"}
    # Alone, the degraded replica still serves.
    rid, _ = r.pick((1, 2, 3), [_sig("deg", mesh_rung=2)])
    assert rid == "deg"
    # The rung is a penalty, not a gate: a mildly degraded idle replica
    # outranks an intact one drowning in queue.
    sigs = [_sig("busy", queue_frac=0.9), _sig("deg", mesh_rung=1)]
    assert r.pick((1, 2, 3), sigs)[0] == "deg"


@pytest.mark.asyncio
async def test_degraded_replica_rebalance_runbook():
    """The drain-then-restore runbook end to end on mock replicas:
    a replica degrades (mesh_rung > 0 in its signals) → the gauge and
    router see it → ``rebalance_degraded`` migrates its pinned sessions
    onto the intact sibling → the replica rebuilds at full mesh → the
    next rebalance is a no-op and the cell reads fully intact again."""
    cell = _mock_cell(n=2)
    await cell.start()
    try:
        await cell.apredict("runbook turn one", session_id="s-rb")
        owner = cell.sessions["s-rb"]
        other = next(r for r in cell.replicas if r != owner)
        # Degrade the owner: its engine now reports a survivor rung.
        cell.replicas[owner].handler.backend.routing_signals = (
            lambda: {"mesh_rung": 1}
        )
        assert cell.replicas[owner].signals().mesh_rung == 1
        report = await cell.rebalance_degraded()
        assert report["degraded"] == [owner]
        assert report["moved"] == 1
        assert cell.sessions["s-rb"] == other
        assert global_metrics.get("cell.degraded_replicas") == 1.0
        # Fresh sessions avoid the degraded replica while it lasts.
        await cell.apredict("fresh while degraded", session_id="s-rb2")
        assert cell.sessions["s-rb2"] == other
        # Replica rebuilt at full mesh: rung back to 0, cell intact.
        del cell.replicas[owner].handler.backend.routing_signals
        report2 = await cell.rebalance_degraded()
        assert report2["degraded"] == [] and report2["moved"] == 0
        assert global_metrics.get("cell.degraded_replicas") == 0.0
    finally:
        await cell.stop()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.asyncio
async def test_corrupted_migration_frame_rejected_byte_identical(
    reference_turns,
):
    """cell.migrate.corrupt rots the wire frame mid-migration: the
    import rejects every entry (counted under cell.migrate_rejected +
    integrity_failures), NO corrupt KV lands on the target — and the
    session's next turn re-prefills there to byte-identical output."""
    from pilottai_tpu.reliability.inject import global_injector

    cell = ServingCell([LLMHandler(_engine_cfg()) for _ in range(2)])
    await cell.start()
    try:
        p = GenerationParams(**GREEDY)
        r1 = await cell.apredict(TURN1, params=p, session_id="s")
        fails0 = global_metrics.get("engine.kvcache.integrity_failures")
        global_injector.arm("cell.migrate.corrupt", value=True, times=1)
        try:
            report = await cell.migrate_session("s")
        finally:
            global_injector.reset()
        assert report["entries"] >= 1
        assert report["accepted"] == 0
        assert report["rejected"] == report["entries"]
        assert global_metrics.get("cell.migrate_rejected") >= 1
        assert (
            global_metrics.get("engine.kvcache.integrity_failures")
            > fails0
        )
        r2 = await cell.apredict(
            TURN1 + r1 + " user: second step?", params=p, session_id="s"
        )
        assert cell.sessions["s"] == report["to"]
        assert (r1, r2) == reference_turns, (
            "rejected migration changed greedy output"
        )
    finally:
        await cell.stop()
