"""Regression tests for issues found in code review of the core runtime."""

import asyncio
import json
import time

import pytest

from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.utils.tracing import Tracer


def test_clone_for_retry_after_deadline_passed():
    # clone/round-trip must not re-reject a deadline that has since passed.
    t = Task(description="x", deadline=time.time() + 0.05)
    time.sleep(0.06)
    clone = t.clone_for_retry()
    assert clone.deadline == t.deadline
    roundtrip = Task(**t.model_dump())
    assert roundtrip.id == t.id


def test_detect_cycle_deep_chain_no_recursion_error():
    n = 3000
    tasks = {
        str(i): Task(id=str(i), description="x", dependencies=[str(i + 1)] if i + 1 < n else [])
        for i in range(n)
    }
    assert Task.detect_cycle(tasks) is None
    tasks[str(n - 1)].dependencies = ["0"]
    assert Task.detect_cycle(tasks) is not None


@pytest.mark.asyncio
async def test_tracer_concurrent_asyncio_tasks_have_independent_stacks():
    tr = Tracer()
    parents = {}

    async def work(name):
        with tr.span(name) as outer:
            await asyncio.sleep(0.01)
            with tr.span(f"{name}.inner") as inner:
                parents[name] = (inner.parent_id, outer.span_id)
                await asyncio.sleep(0.01)

    await asyncio.gather(work("a"), work("b"), work("c"))
    for name, (parent_id, outer_id) in parents.items():
        assert parent_id == outer_id, f"span parentage corrupted for {name}"


def test_prompt_no_cross_kwarg_injection():
    pm = PromptManager("agent")
    out = pm.format_prompt(
        "step_planning",
        task="user asked about the {history} feature and {{braces}}",
        history="SECRET-STEP-LOG",
    )
    # The literal {history} inside the task VALUE must survive untouched.
    assert "user asked about the {history} feature and {{braces}}" in out
    assert out.count("SECRET-STEP-LOG") == 1


def test_agent_config_secret_roundtrip(tmp_path):
    cfg = AgentConfig(role="r", llm=LLMConfig(api_key="sk-real-key"))
    path = tmp_path / "cfg.json"
    cfg.save(path)
    on_disk = json.loads(path.read_text())
    assert on_disk["llm"]["api_key"] == "sk-real-key"
    loaded = AgentConfig.load(path)
    assert loaded.llm.api_key.get_secret_value() == "sk-real-key"


def test_setup_logging_explicit_config_wins_after_autoconfig(tmp_path):
    import logging as stdlog

    from pilottai_tpu.core.config import LogConfig
    from pilottai_tpu.utils import logging as plog

    plog.get_logger("early").info("auto-configures with defaults")
    plog.setup_logging(LogConfig(log_to_file=True, log_dir=str(tmp_path)))
    root = stdlog.getLogger("pilottai_tpu")
    file_handlers = [h for h in root.handlers if isinstance(h, stdlog.FileHandler)]
    assert file_handlers, "explicit setup_logging must attach file handlers"
    plog.setup_logging(LogConfig())  # restore console-only for other tests


@pytest.mark.asyncio
async def test_stop_settles_inflight_before_journal_close(tmp_path):
    """Advisor: a task finishing after stop() used to hit record_status on
    a closed journal inside _finalize; stop must settle in-flight work
    first."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import ServeConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.serve import Serve

    backend = MockBackend(latency=0.5)  # slow agent steps
    agent = BaseAgent(
        config=AgentConfig(role="processor"),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=backend),
    )
    serve = Serve(
        name="t", agents=[agent],
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(
            journal_path=str(tmp_path / "j.jsonl"), decomposition_enabled=False,
        ),
    )
    await serve.start()
    await serve.add_task("slow task mid-flight at stop")
    await asyncio.sleep(0.2)  # execution underway
    await serve.stop()  # must not raise / log journal-closed errors
    assert serve.journal is not None


@pytest.mark.asyncio
async def test_wait_for_recovered_cancelled_task_returns_immediately(tmp_path):
    """Advisor: wait_for on a journal-recovered CANCELLED task (result
    null) used to hang until timeout."""
    from pilottai_tpu.checkpoint.journal import TaskJournal
    from pilottai_tpu.core.config import ServeConfig
    from pilottai_tpu.core.task import TaskStatus
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.serve import Serve

    path = str(tmp_path / "j.jsonl")
    journal = TaskJournal(path)
    t = Task(description="evicted")
    t.status = TaskStatus.CANCELLED
    journal.record_task(t)
    journal.record_status(t)
    journal.close()

    serve = Serve(
        name="t",
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(journal_path=path, decomposition_enabled=False),
    )
    await serve.recover()
    result = await asyncio.wait_for(serve.wait_for(t.id), timeout=2)
    assert not result.success
    assert "cancelled" in (result.error or "").lower() or "CANCELLED" in (result.error or "")


def test_vector_store_import_adopts_snapshot_geometry():
    """Advisor: restoring a snapshot saved with a different capacity used
    to leave stale capacity/dim and corrupt ring indexing."""
    import numpy as np

    from pilottai_tpu.memory.semantic import _VectorStore

    src = _VectorStore(capacity=4, dim=8)
    for i in range(3):
        v = np.zeros(8, np.float32)
        v[i] = 1.0
        src.add(i, v)
    snap = src.export_arrays()

    dst = _VectorStore(capacity=16, dim=32)  # different config
    dst.import_arrays(snap)
    assert dst.capacity == 4 and dst.dim == 8
    # add() must wrap at the snapshot capacity, not the constructor's.
    for i in range(3, 9):
        v = np.zeros(8, np.float32)
        v[i % 8] = 1.0
        dst.add(i, v)
    hits = dst.search(np.eye(8, dtype=np.float32)[5 % 8], k=2)
    assert hits and all(eid < 9 for eid, _ in hits)


@pytest.mark.asyncio
async def test_memory_import_rejects_dim_mismatch():
    import numpy as np

    from pilottai_tpu.memory.semantic import EnhancedMemory

    class FakeEmbedder:
        dim = 8

        async def encode(self, texts):
            return np.ones((len(texts), 8), np.float32)

    mem = EnhancedMemory(embedder=FakeEmbedder())
    state = {
        "items": [], "order": [], "next_id": 0, "task_history": {},
        "interactions": [], "patterns": [],
        "vector_arrays": {
            "vectors": np.zeros((4, 16), np.float32),  # dim 16 != 8
            "row_ids": np.full((4,), -1, np.int64),
            "next_row": np.asarray([0]),
        },
    }
    with pytest.raises(ValueError, match="dim"):
        await mem.import_state(state)


@pytest.mark.asyncio
async def test_stop_resolves_untimed_waiters(tmp_path):
    """Review finding: stop() cancelled in-flight tasks without finalizing,
    stranding a wait_for with no timeout forever."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import ServeConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.serve import Serve

    agent = BaseAgent(
        config=AgentConfig(role="processor"),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend(latency=5.0)),
    )
    serve = Serve(
        name="t", agents=[agent],
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(
            journal_path=str(tmp_path / "j.jsonl"), decomposition_enabled=False,
        ),
    )
    await serve.start()
    task = await serve.add_task("very slow work")
    waiter = asyncio.ensure_future(serve.wait_for(task.id, timeout=120))
    await asyncio.sleep(0.2)
    await serve.stop()
    result = await asyncio.wait_for(waiter, timeout=2)
    assert not result.success and "stopped" in (result.error or "")
