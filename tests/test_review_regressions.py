"""Regression tests for issues found in code review of the core runtime."""

import asyncio
import json
import time

import pytest

from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.utils.tracing import Tracer


def test_clone_for_retry_after_deadline_passed():
    # clone/round-trip must not re-reject a deadline that has since passed.
    t = Task(description="x", deadline=time.time() + 0.05)
    time.sleep(0.06)
    clone = t.clone_for_retry()
    assert clone.deadline == t.deadline
    roundtrip = Task(**t.model_dump())
    assert roundtrip.id == t.id


def test_detect_cycle_deep_chain_no_recursion_error():
    n = 3000
    tasks = {
        str(i): Task(id=str(i), description="x", dependencies=[str(i + 1)] if i + 1 < n else [])
        for i in range(n)
    }
    assert Task.detect_cycle(tasks) is None
    tasks[str(n - 1)].dependencies = ["0"]
    assert Task.detect_cycle(tasks) is not None


@pytest.mark.asyncio
async def test_tracer_concurrent_asyncio_tasks_have_independent_stacks():
    tr = Tracer()
    parents = {}

    async def work(name):
        with tr.span(name) as outer:
            await asyncio.sleep(0.01)
            with tr.span(f"{name}.inner") as inner:
                parents[name] = (inner.parent_id, outer.span_id)
                await asyncio.sleep(0.01)

    await asyncio.gather(work("a"), work("b"), work("c"))
    for name, (parent_id, outer_id) in parents.items():
        assert parent_id == outer_id, f"span parentage corrupted for {name}"


def test_prompt_no_cross_kwarg_injection():
    pm = PromptManager("agent")
    out = pm.format_prompt(
        "step_planning",
        task="user asked about the {history} feature and {{braces}}",
        history="SECRET-STEP-LOG",
    )
    # The literal {history} inside the task VALUE must survive untouched.
    assert "user asked about the {history} feature and {{braces}}" in out
    assert out.count("SECRET-STEP-LOG") == 1


def test_agent_config_secret_roundtrip(tmp_path):
    cfg = AgentConfig(role="r", llm=LLMConfig(api_key="sk-real-key"))
    path = tmp_path / "cfg.json"
    cfg.save(path)
    on_disk = json.loads(path.read_text())
    assert on_disk["llm"]["api_key"] == "sk-real-key"
    loaded = AgentConfig.load(path)
    assert loaded.llm.api_key.get_secret_value() == "sk-real-key"


def test_setup_logging_explicit_config_wins_after_autoconfig(tmp_path):
    import logging as stdlog

    from pilottai_tpu.core.config import LogConfig
    from pilottai_tpu.utils import logging as plog

    plog.get_logger("early").info("auto-configures with defaults")
    plog.setup_logging(LogConfig(log_to_file=True, log_dir=str(tmp_path)))
    root = stdlog.getLogger("pilottai_tpu")
    file_handlers = [h for h in root.handlers if isinstance(h, stdlog.FileHandler)]
    assert file_handlers, "explicit setup_logging must attach file handlers"
    plog.setup_logging(LogConfig())  # restore console-only for other tests
