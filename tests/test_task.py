"""Tests for the Task model lifecycle, priorities and dependency checks."""

import time

import pytest

from pilottai_tpu.core.task import (
    ResourceLockRegistry,
    Task,
    TaskPriority,
    TaskResult,
    TaskStatus,
)


def test_task_defaults():
    t = Task(description="do a thing")
    assert t.status == TaskStatus.PENDING
    assert t.priority == TaskPriority.NORMAL
    assert t.id and t.created_at > 0


def test_priority_is_numeric():
    # The reference compared string enums lexicographically (SURVEY §2.12-h);
    # priorities here must order numerically.
    assert TaskPriority.CRITICAL > TaskPriority.HIGH > TaskPriority.NORMAL > TaskPriority.LOW
    assert TaskPriority.coerce("high") == TaskPriority.HIGH
    assert TaskPriority.coerce(2) == TaskPriority.HIGH


def test_lifecycle_transitions():
    t = Task(description="x")
    t.mark_queued()
    assert t.status == TaskStatus.QUEUED
    t.mark_started(agent_id="a1")
    assert t.status == TaskStatus.IN_PROGRESS and t.agent_id == "a1"
    t.mark_completed(TaskResult(success=True, output="ok"))
    assert t.status == TaskStatus.COMPLETED
    assert t.result.output == "ok"
    assert t.execution_time is not None


def test_retry_budget():
    t = Task(description="x", max_retries=2)
    t.mark_started()
    t.mark_failed("boom")
    assert t.prepare_retry() and t.retry_count == 1
    assert t.prepare_retry() and t.retry_count == 2
    assert not t.prepare_retry()


def test_self_dependency_rejected():
    with pytest.raises(ValueError):
        Task(id="t1", description="x", dependencies=["t1"])


def test_cycle_detection():
    a = Task(id="a", description="a", dependencies=["b"])
    b = Task(id="b", description="b", dependencies=["c"])
    c = Task(id="c", description="c", dependencies=["a"])
    cycle = Task.detect_cycle({"a": a, "b": b, "c": c})
    assert cycle is not None
    ok_c = Task(id="c", description="c")
    assert Task.detect_cycle({"a": a, "b": b, "c": ok_c}) is None


def test_deadline_must_be_future():
    with pytest.raises(ValueError):
        Task(description="x", deadline=time.time() - 10)


def test_clone_for_retry():
    t = Task(description="x", payload={"k": 1})
    t.mark_started()
    t.mark_failed("err")
    clone = t.clone_for_retry()
    assert clone.id != t.id
    assert clone.status == TaskStatus.PENDING
    assert clone.metadata["retry_of"] == t.id
    assert clone.payload == {"k": 1}


@pytest.mark.asyncio
async def test_resource_locks_sorted_acquisition():
    reg = ResourceLockRegistry()
    order = []

    async with reg.acquire("b", "a"):
        order.append("outer")
        assert reg.get("a").locked() and reg.get("b").locked()
    assert not reg.get("a").locked() and not reg.get("b").locked()
    assert order == ["outer"]


def test_to_prompt_contains_fields():
    t = Task(description="summarize doc", type="summarize", tools=["reader"])
    prompt = t.to_prompt()
    assert "summarize doc" in prompt and "reader" in prompt and t.id in prompt


def test_task_result_resource_cleanup(tmp_path):
    """TaskResult owns registered handles/temp files (reference
    ``core/task.py:29-66``): cleanup closes, unlinks, and is idempotent."""
    tmp = tmp_path / "scratch.bin"
    tmp.write_bytes(b"x" * 16)
    handle = open(tmp_path / "out.log", "w")
    res = TaskResult(success=True, output="done")
    res.register_file_handle(handle)
    res.register_temp_file(tmp)
    assert not res.resources_cleaned
    res.cleanup_resources()
    assert res.resources_cleaned
    assert handle.closed
    assert not tmp.exists()
    res.cleanup_resources()  # idempotent
    assert "cleanup_errors" not in res.metadata
    # Excluded from serialization.
    assert "file_handles" not in res.model_dump()


def test_task_cleanup_cascades_to_result(tmp_path):
    tmp = tmp_path / "stage.tmp"
    tmp.write_text("intermediate")
    t = Task(description="with resources")
    t.register_temp_file(tmp)
    res = TaskResult(success=True)
    rtmp = tmp_path / "result.tmp"
    rtmp.write_text("r")
    res.register_temp_file(rtmp)
    t.mark_completed(res)
    t.cleanup_resources()
    assert not tmp.exists() and not rtmp.exists()
    assert res.resources_cleaned


def test_task_output_file_written_on_completion(tmp_path):
    """Unlike the reference (declares output_file, never writes it),
    completion persists the output; structured outputs as JSON."""
    import json

    out = tmp_path / "answer.json"
    t = Task(description="write me", output_file=str(out))
    t.mark_completed(TaskResult(success=True, output={"answer": 42}))
    assert json.loads(out.read_text()) == {"answer": 42}

    txt = tmp_path / "answer.txt"
    t2 = Task(description="text", output_file=str(txt))
    t2.mark_completed(TaskResult(success=True, output="plain text"))
    assert txt.read_text() == "plain text"


def test_task_output_file_rejects_directory(tmp_path):
    with pytest.raises(ValueError):
        Task(description="bad", output_file=str(tmp_path))
