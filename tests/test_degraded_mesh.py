"""Degraded-mesh serving acceptance (ISSUE 16 tentpole).

The engine-integrated half of tests/test_meshplan.py: kill a device of
a ``{'model':2,'data':2}`` mesh mid-decode and pin the contract —
the engine re-plans onto the survivor sub-mesh (default ladder rung
``model2``), re-places weights/KV, drains the in-flight requests
through snapshot/re-admit, and greedy output stays byte-identical.
Fast tests cover the raise variant, the hang variant (per-shard
heartbeat triage riding the PR 8 watchdog), and the ladder-exhausted
contract (in-flight requests fail with the ORIGINAL exception). The
slow matrix certifies byte-identity across dense/paged × spec ×
int8-KV/int4-weights, same shape as tests/test_multichip.py.
"""

import time

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.metrics import global_metrics

MESH = {"model": 2, "data": 2}


def _mesh(shape=None):
    return create_mesh(MeshConfig.from_dict(shape or MESH))


@pytest.fixture(autouse=True)
def _clean_injector():
    global_injector.reset()
    yield
    global_injector.reset()


def _batcher(**overrides):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kwargs = dict(
        n_slots=2, max_seq_len=64, cache_dtype=jnp.float32, chunk_size=4,
        mesh=_mesh(MESH), recovery_max_attempts=2, use_pallas=False,
    )
    kwargs.update(overrides)
    return ContinuousBatcher(cfg, params, **kwargs)


def _wave(b, max_new=12, timeout=300):
    prompts = [[3, 4, 5], [6, 7]]
    futs = [
        b.submit(GenRequest(prompt_ids=list(p), max_new_tokens=max_new))
        for p in prompts
    ]
    return [f.result(timeout=timeout) for f in futs]


# --------------------------------------------------------------------- #
# The acceptance bar — shard loss mid-decode, byte-identical. Real
# 4-device engines on the shared-core virtual platform are minutes of
# wall each, so these live in the chaos CI lane (slow+chaos), keeping
# tier-1 at its seed runtime; the pure ladder logic stays in tier-1
# via test_meshplan.py.
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.chaos
def test_shard_loss_mid_decode_replans_byte_identical():
    """Device 1 of {'model':2,'data':2} raises mid-decode (skip=1: the
    SECOND dispatch, so the wave is genuinely in flight): the engine
    classifies the loss, re-plans to the model2 rung over the three
    survivors, re-places weights/KV, re-admits from snapshots — and the
    greedy output matches the unfaulted run byte for byte while every
    degradation gauge tells the truth."""
    b = _batcher()
    b.start()
    try:
        ref = _wave(b)
        losses = global_metrics.get("engine.shard_losses")
        global_injector.arm("mesh.shard_loss", value=1, times=1, skip=1)
        got = _wave(b)
        assert got == ref
        assert global_injector.fired("mesh.shard_loss") == 1

        ladder = b._mesh_ladder
        assert ladder is not None
        assert ladder.rung == 1
        assert ladder.lost() == [1]
        assert global_metrics.get("engine.shard_losses") == losses + 1
        assert global_metrics.get("engine.mesh_plan") == 1.0

        mesh = b.get_metrics()["mesh"]
        assert mesh["rung"] == 1
        assert mesh["plan"] == "model2"
        assert mesh["lost_devices"] == [1]
        assert mesh["n_chips"] == 2
        # The degraded rung rides routing_signals into the cell router.
        assert b.routing_signals()["mesh_rung"] == 1

        # The degraded engine keeps serving correctly after the drain.
        assert _wave(b) == ref
    finally:
        b.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_hung_shard_detected_by_heartbeat_triage():
    """The hang variant: the shard stops answering WITHOUT raising, and
    the engine itself stays healthy — only the per-shard heartbeat
    (fold-path ``beat_all`` vs the watchdog's staleness bar) tells a
    frozen shard from its beating siblings and triggers the re-plan."""
    b = _batcher(watchdog_stall_s=0.5)
    b.start()
    try:
        ref = _wave(b)
        global_injector.arm(
            "mesh.shard_loss",
            value={"hang": True, "device": 2},
            times=1, skip=1,
        )
        assert _wave(b) == ref  # freezing the stamp wedges nothing
        time.sleep(0.8)  # let the frozen stamp cross the staleness bar
        ladder = b._mesh_ladder
        deadline = time.monotonic() + 30
        while ladder.rung == 0 and time.monotonic() < deadline:
            _wave(b, max_new=4)  # folds run the triage
            time.sleep(0.05)
        assert ladder.rung == 1
        assert ladder.lost() == [2]
        assert _wave(b) == ref
    finally:
        b.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_ladder_exhausted_fails_inflight_with_original_exception():
    """A one-rung ladder (boot plan only) has nowhere to go after a
    loss: the recovery contract ends and the in-flight requests fail
    with the ORIGINAL shard-loss exception — no silent retry loop, no
    wrong-layout serving."""
    b = _batcher(mesh_ladder=[{"model": 2, "data": 2}])
    b.start()
    try:
        _wave(b)  # healthy first
        global_injector.arm("mesh.shard_loss", value=0, times=1, skip=1)
        futs = [
            b.submit(GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=12)),
            b.submit(GenRequest(prompt_ids=[6, 7], max_new_tokens=12)),
        ]
        for f in futs:
            with pytest.raises(RuntimeError, match="lost shard: device 0"):
                f.result(timeout=300)
        ladder = b._mesh_ladder
        assert ladder.lost() == [0]
        assert not ladder.viable()
    finally:
        b.stop()


def test_mesh_ladder_off_disables_the_fault_domain():
    """mesh_ladder='off': no ladder, no mesh-rung gauges — the PR 8
    same-mesh rebuild is the only recovery (the pre-ISSUE 16 engine)."""
    b = _batcher(mesh_ladder="off")
    try:
        assert b._mesh_ladder is None
        assert "rung" not in b.get_metrics()["mesh"]
        assert b.routing_signals()["mesh_rung"] == 0
    finally:
        b.stop()


# --------------------------------------------------------------------- #
# Slow: byte-identity matrix on the degraded path (chaos CI lane)
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize(
    "paged,speculate,kv_int8,weight_quant",
    [
        (False, 0, False, None),
        (False, 4, True, None),
        (True, 0, True, None),
        (True, 4, False, None),
        (False, 4, False, "int4"),
        (True, 0, False, "int4"),
    ],
    ids=[
        "dense", "dense-spec-int8kv", "paged-int8kv", "paged-spec",
        "dense-spec-int4", "paged-int4",
    ],
)
@pytest.mark.asyncio
async def test_degraded_greedy_byte_identity_matrix(
    paged, speculate, kv_int8, weight_quant,
):
    """Shard loss mid-decode across every cache/speculation/quant
    combination the serving path has: greedy output on the degraded
    engine byte-identical to the unfaulted sharded run."""
    from tests.test_multichip import _generate_all

    ref = await _generate_all(
        MESH, paged=paged, speculate=speculate, kv_int8=kv_int8,
        weight_quant=weight_quant,
    )
    losses = global_metrics.get("engine.shard_losses")
    global_injector.arm("mesh.shard_loss", value=1, times=1, skip=1)
    try:
        got = await _generate_all(
            MESH, paged=paged, speculate=speculate, kv_int8=kv_int8,
            weight_quant=weight_quant,
        )
    finally:
        global_injector.reset()
    assert got == ref
    assert any(s for s in ref)
    assert global_metrics.get("engine.shard_losses") == losses + 1
