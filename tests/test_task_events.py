"""Task event bus: live lifecycle feed from Serve, rollup across
decomposition, and the server's SSE task stream."""

import asyncio
import json

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.serve import Serve


def _mock_llm(**kwargs) -> LLMHandler:
    return LLMHandler(LLMConfig(provider="mock"), backend=MockBackend(**kwargs))


def _drain(q: asyncio.Queue):
    out = []
    while not q.empty():
        out.append(q.get_nowait())
    return out


@pytest.mark.asyncio
async def test_event_sequence_simple_task():
    llm = _mock_llm()
    serve = Serve(
        name="events", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    try:
        task = serve.prepare_task("count the widgets")
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task)
        assert result.success
        events = [e["event"] for e in _drain(q)]
        # Core lifecycle, in order (step events may interleave).
        order = [e for e in events
                 if e in ("received", "analyzed", "queued", "assigned",
                          "completed")]
        assert order == ["received", "analyzed", "queued", "assigned",
                         "completed"]
        assert "step" in events  # agent step_callback wired by default
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_subtask_events_roll_up_to_parent():
    def force_decomposition(prompt):
        if '"requires_decomposition"' in prompt:
            return {"requires_decomposition": True, "complexity": 7,
                    "estimated_resources": {}}
        return None  # fall through to protocol defaults (incl. subtasks)

    llm = _mock_llm(responders=[force_decomposition])
    serve = Serve(
        name="rollup", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=True),
    )
    await serve.start()
    try:
        task = serve.prepare_task("produce the annual report")
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task, timeout=60)
        assert result.success
        events = _drain(q)
        kinds = [e["event"] for e in events]
        assert "decomposed" in kinds
        # Subtask lifecycle surfaced through the PARENT subscription.
        sub_ids = {e["task_id"] for e in events if e["task_id"] != task.id}
        assert len(sub_ids) >= 3  # the mock decomposes into 3 subtasks
        assert any(
            e["event"] == "completed" and e["task_id"] in sub_ids
            for e in events
        )
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_slow_subscriber_drops_oldest_not_blocks():
    llm = _mock_llm()
    serve = Serve(
        name="ring", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    try:
        task = serve.prepare_task("tiny buffer")
        q = serve.subscribe_events(task.id, max_buffer=1)
        result = await serve.execute_task(task)
        assert result.success
        events = _drain(q)
        assert len(events) == 1  # ring kept only the newest
        assert events[0]["event"] == "completed"
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_server_task_stream_sse():
    from pilottai_tpu.server import APIServer
    from tests.test_server import _request

    llm = _mock_llm()
    serve = Serve(
        name="sse-tasks", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    server = await APIServer(llm, serve=serve).start()
    try:
        status, hdrs, body = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "stream the lifecycle", "stream": True},
        )
        assert status == 200
        assert hdrs["content-type"] == "text/event-stream"
        events = [
            line[len("data: "):]
            for line in body.decode().split("\n")
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        parsed = [json.loads(e) for e in events[:-1]]
        kinds = [p.get("event") for p in parsed if "event" in p]
        assert "received" in kinds and "completed" in kinds
        final = parsed[-1]
        assert final.get("object") == "task.result" and final["success"]
    finally:
        await server.stop()
        await serve.stop()
