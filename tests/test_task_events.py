"""Task event bus: live lifecycle feed from Serve, rollup across
decomposition, and the server's SSE task stream."""

import asyncio
import json

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.serve import Serve


def _mock_llm(**kwargs) -> LLMHandler:
    return LLMHandler(LLMConfig(provider="mock"), backend=MockBackend(**kwargs))


def _drain(q: asyncio.Queue):
    out = []
    while not q.empty():
        out.append(q.get_nowait())
    return out


@pytest.mark.asyncio
async def test_event_sequence_simple_task():
    llm = _mock_llm()
    serve = Serve(
        name="events", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    try:
        task = serve.prepare_task("count the widgets")
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task)
        assert result.success
        events = [e["event"] for e in _drain(q)]
        # Core lifecycle, in order (step events may interleave).
        order = [e for e in events
                 if e in ("received", "analyzed", "queued", "assigned",
                          "completed")]
        assert order == ["received", "analyzed", "queued", "assigned",
                         "completed"]
        assert "step" in events  # agent step_callback wired by default
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_subtask_events_roll_up_to_parent():
    def force_decomposition(prompt):
        if '"requires_decomposition"' in prompt:
            return {"requires_decomposition": True, "complexity": 7,
                    "estimated_resources": {}}
        return None  # fall through to protocol defaults (incl. subtasks)

    llm = _mock_llm(responders=[force_decomposition])
    serve = Serve(
        name="rollup", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=True),
    )
    await serve.start()
    try:
        task = serve.prepare_task("produce the annual report")
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task, timeout=60)
        assert result.success
        events = _drain(q)
        kinds = [e["event"] for e in events]
        assert "decomposed" in kinds
        # Subtask lifecycle surfaced through the PARENT subscription.
        sub_ids = {e["task_id"] for e in events if e["task_id"] != task.id}
        assert len(sub_ids) >= 3  # the mock decomposes into 3 subtasks
        assert any(
            e["event"] == "completed" and e["task_id"] in sub_ids
            for e in events
        )
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_slow_subscriber_drops_oldest_not_blocks():
    llm = _mock_llm()
    serve = Serve(
        name="ring", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    try:
        task = serve.prepare_task("tiny buffer")
        q = serve.subscribe_events(task.id, max_buffer=1)
        result = await serve.execute_task(task)
        assert result.success
        events = _drain(q)
        assert len(events) == 1  # ring kept only the newest
        assert events[0]["event"] == "completed"
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


def _first_occurrence_order(events, task_id):
    seen = []
    for e in events:
        if e["task_id"] == task_id and e["event"] not in seen:
            seen.append(e["event"])
    return seen


@pytest.mark.asyncio
async def test_event_order_matches_dag_marks_for_fanout():
    """The event stream and the DAG ledger stamp lifecycle transitions
    with ONE clock (serve._emit_event feeds both), so for a fan-out
    task the queued -> started -> completed ordering must agree between
    the two surfaces — for the parent AND its subtasks."""
    from pilottai_tpu.obs import global_dag

    def force_decomposition(prompt):
        if '"requires_decomposition"' in prompt:
            return {"requires_decomposition": True, "complexity": 7,
                    "estimated_resources": {}}
        return None

    llm = _mock_llm(responders=[force_decomposition])
    serve = Serve(
        name="dag-events", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=True),
    )
    await serve.start()
    try:
        task = serve.prepare_task("produce the annual report")
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task, timeout=60)
        assert result.success
        events = _drain(q)

        # Parent: the ledger's marks dict is ordered by timestamp; its
        # order over the parent's lifecycle events must equal the event
        # stream's first-occurrence order.
        d = global_dag.describe(task.id)
        assert d is not None
        event_order = [
            e for e in _first_occurrence_order(events, task.id)
            if e in d["marks"]
        ]
        mark_order = [k for k in d["marks"] if k in event_order]
        assert event_order == mark_order
        assert "decomposed" in d["marks"]

        # Every subtask: queued <= assigned <= completed on the ledger
        # clock, matching the stream's ordering guarantees.
        sub_ids = {e["task_id"] for e in events if e["task_id"] != task.id}
        assert len(sub_ids) >= 3
        for sid in sub_ids:
            sd = global_dag.describe(sid)
            assert sd is not None, sid
            marks = sd["marks"]
            assert marks["queued"] <= marks["assigned"] <= marks["completed"]
            sub_order = [
                e for e in _first_occurrence_order(events, sid)
                if e in marks
            ]
            assert sub_order == [k for k in marks if k in sub_order]
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_cancelled_eviction_closes_dag_with_event_parity():
    """Queue eviction (the cancelled path): the evicted task's DAG must
    finish with status 'cancelled' and its marks must cover the same
    lifecycle the event stream reported."""
    from pilottai_tpu.obs import global_dag

    llm = _mock_llm()
    serve = Serve(
        name="evict-dag", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False, max_queue_size=1),
    )
    # Deliberately NOT started: the processor must not drain the queue
    # before the higher-priority arrival evicts the low one.
    low = serve.prepare_task(
        {"description": "backlog filler", "priority": "low"}
    )
    q = serve.subscribe_events(low.id)
    try:
        from pilottai_tpu.utils.metrics import global_metrics

        cancelled0 = global_metrics.get("task.cancelled")
        failed0 = global_metrics.get("task.failed")
        await serve.add_task(low)
        await serve.add_task(
            {"description": "urgent work", "priority": "critical"}
        )
        events = _drain(q)
        kinds = [e["event"] for e in events]
        assert "queued" in kinds and "failed" in kinds
        d = global_dag.describe(low.id)
        assert d is not None and d["status"] == "cancelled"
        assert d["marks"]["queued"] <= d["marks"]["failed"]
        # Eviction is routine cancellation, not a failure — it must land
        # in task.cancelled, never inflate task.failed.
        assert global_metrics.get("task.cancelled") == cancelled0 + 1
        assert global_metrics.get("task.failed") == failed0
    finally:
        serve.unsubscribe_events(low.id, q)
        # The un-started serve still holds the urgent task's dag open.
        for t in serve.task_queue.snapshot():
            global_dag.finish(t.id, "cancelled")


@pytest.mark.asyncio
async def test_expired_task_closes_dag_as_failed():
    """The expired path: a task whose budget elapses mid-execution must
    close its DAG as failed, with the failed mark after assigned."""
    from pilottai_tpu.obs import global_dag

    llm = _mock_llm(latency=0.3)  # each LLM step outlives the budget
    serve = Serve(
        name="expire-dag", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    try:
        # Budget on the TASK (not the caller wait): the orchestrator's
        # wait_for kills execution at 0.5 s while the caller keeps a
        # comfortable wait — no race between the two timers.
        task = serve.prepare_task(
            {"description": "doomed to expire", "timeout": 0.5}
        )
        q = serve.subscribe_events(task.id)
        result = await serve.execute_task(task)
        assert not result.success
        events = _drain(q)
        kinds = [e["event"] for e in events]
        assert "assigned" in kinds and "failed" in kinds
        d = global_dag.describe(task.id)
        assert d is not None and d["status"] == "failed"
        assert d["marks"]["assigned"] <= d["marks"]["failed"]
        # The breakdown still reconciles on the failure path.
        assert d["breakdown"]["critical_path_s"] == pytest.approx(
            d["breakdown"]["e2e_s"], rel=0.15
        )
    finally:
        await serve.stop()
        serve.unsubscribe_events(task.id, q)


@pytest.mark.asyncio
async def test_server_task_stream_sse():
    from pilottai_tpu.server import APIServer
    from tests.test_server import _request

    llm = _mock_llm()
    serve = Serve(
        name="sse-tasks", manager_llm=llm,
        agents=[BaseAgent(
            config=AgentConfig(role="worker", specializations=["generic"]),
            llm=llm,
        )],
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    server = await APIServer(llm, serve=serve).start()
    try:
        status, hdrs, body = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "stream the lifecycle", "stream": True},
        )
        assert status == 200
        assert hdrs["content-type"] == "text/event-stream"
        events = [
            line[len("data: "):]
            for line in body.decode().split("\n")
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        parsed = [json.loads(e) for e in events[:-1]]
        kinds = [p.get("event") for p in parsed if "event" in p]
        assert "received" in kinds and "completed" in kinds
        final = parsed[-1]
        assert final.get("object") == "task.result" and final["success"]
    finally:
        await server.stop()
        await serve.stop()
