"""Regressions for the agent queue ghost-slot findings: detached tasks must
free capacity immediately, and rebalance rollback must never orphan work."""

import asyncio

import pytest

from pilottai_tpu.core.agent import AgentTaskQueue, BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.handler import LLMHandler


def worker(**cfg):
    return BaseAgent(config=AgentConfig(role="w", **cfg),
                     llm=LLMHandler(LLMConfig(provider="mock")))


def test_removed_tasks_free_capacity_immediately():
    q = AgentTaskQueue(maxsize=2)
    a, b = Task(description="a"), Task(description="b")
    q.put_nowait(a); q.put_nowait(b)
    with pytest.raises(asyncio.QueueFull):
        q.put_nowait(Task(description="c"))
    q.remove(a.id)
    q.put_nowait(Task(description="d"))  # ghost slot must not block this
    assert q.qsize() == 2
    got = [q.get_nowait().description, q.get_nowait().description]
    assert got == ["b", "d"]  # removed 'a' skipped
    with pytest.raises(asyncio.QueueEmpty):
        q.get_nowait()


@pytest.mark.asyncio
async def test_agent_accepts_after_rebalance_detach():
    agent = worker(max_queue_size=2)
    await agent.start()
    t1, t2 = Task(description="t1"), Task(description="t2")
    await agent.add_task(t1); await agent.add_task(t2)
    agent.remove_task(t1.id)
    await agent.add_task(Task(description="t3"))  # must not raise
    assert agent.task_queue.qsize() == 2


@pytest.mark.asyncio
async def test_queue_get_timeout_returns_none():
    q = AgentTaskQueue(maxsize=1)
    assert await q.get(timeout=0.05) is None


@pytest.mark.asyncio
async def test_queue_worker_skips_detached():
    agent = worker(max_queue_size=4)
    await agent.start()
    keep, drop = Task(description="keep"), Task(description="drop")
    await agent.add_task(drop); await agent.add_task(keep)
    agent.remove_task(drop.id)
    agent.start_queue_worker()
    for _ in range(100):
        if agent.task_metrics["completed"] >= 1:
            break
        await asyncio.sleep(0.05)
    await agent.stop()
    assert agent.task_metrics["completed"] == 1
    ids = [h["task_id"] for h in agent.task_history]
    assert ids == [keep.id]
