"""API server tests: OpenAI-compatible wire format, SSE streaming, task
submission, auth, and error handling — all against the mock provider
(SURVEY §4: deterministic fakes at every boundary)."""

import asyncio
import json

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.server import APIServer


async def _request(
    port: int,
    method: str,
    path: str,
    body: dict | None = None,
    token: str | None = None,
    raw_body: bytes | None = None,
    headers: dict | None = None,
):
    """Minimal HTTP/1.1 client over asyncio streams. Returns
    (status, headers, body_bytes)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = raw_body if raw_body is not None else (
        json.dumps(body).encode() if body is not None else b""
    )
    extra = headers or {}
    headers = f"Content-Length: {len(payload)}\r\n"
    for key, value in extra.items():
        headers += f"{key}: {value}\r\n"
    if token:
        headers += f"Authorization: Bearer {token}\r\n"
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n{headers}"
        f"Connection: close\r\n\r\n".encode() + payload
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body_bytes = raw.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    status = int(lines[0].split(" ")[1])
    hdrs = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    return status, hdrs, body_bytes


def _mock_handler(**mock_kwargs) -> LLMHandler:
    return LLMHandler(
        LLMConfig(provider="mock", model_name="mock-1"),
        backend=MockBackend(**mock_kwargs),
    )


@pytest.mark.asyncio
async def test_chat_completion_roundtrip():
    server = await APIServer(_mock_handler()).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hello there"}]},
        )
        assert status == 200
        data = json.loads(body)
        assert data["object"] == "chat.completion"
        assert data["choices"][0]["message"]["role"] == "assistant"
        assert data["choices"][0]["message"]["content"]
        assert data["usage"]["total_tokens"] >= 0
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_chat_completion_stream_sse():
    server = await APIServer(
        _mock_handler(script=["alpha beta gamma"])
    ).start()
    try:
        status, hdrs, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}], "stream": True},
        )
        assert status == 200
        assert hdrs["content-type"] == "text/event-stream"
        events = [
            line[len("data: "):]
            for line in body.decode().split("\n")
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        assert text == "alpha beta gamma"
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_tools_map_to_tool_calls():
    server = await APIServer(_mock_handler(script=[
        '{"tool_call": {"name": "search", "arguments": {"q": "tpu"}}}'
    ])).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {
                "messages": [{"role": "user", "content": "find it"}],
                "tools": [{
                    "type": "function",
                    "function": {"name": "search", "description": "web"},
                }],
            },
        )
        assert status == 200
        calls = json.loads(body)["choices"][0]["message"]["tool_calls"]
        assert calls[0]["function"]["name"] == "search"
        assert json.loads(calls[0]["function"]["arguments"]) == {"q": "tpu"}
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_stream_with_tools_emits_tool_call_delta():
    server = await APIServer(_mock_handler(script=[
        '{"tool_call": {"name": "search", "arguments": {"q": "tpu"}}}'
    ])).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {
                "messages": [{"role": "user", "content": "find it"}],
                "stream": True,
                "tools": [{
                    "type": "function",
                    "function": {"name": "search", "description": "web"},
                }],
            },
        )
        assert status == 200
        chunks = [
            json.loads(line[len("data: "):])
            for line in body.decode().split("\n")
            if line.startswith("data: ") and line != "data: [DONE]"
        ]
        tool_deltas = [
            c for c in chunks
            if c["choices"][0]["delta"].get("tool_calls")
        ]
        assert len(tool_deltas) == 1
        call = tool_deltas[0]["choices"][0]["delta"]["tool_calls"][0]
        assert call["function"]["name"] == "search"
        assert chunks[-1]["choices"][0]["finish_reason"] == "tool_calls"
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_multi_model_routing():
    """A handler dict routes by the request's model field; unknown
    models 404 with OpenAI's model_not_found type."""
    server = await APIServer({
        "alpha": LLMHandler(
            LLMConfig(provider="mock", model_name="alpha"),
            backend=MockBackend(script=["from alpha"], model_name="alpha"),
        ),
        "beta": LLMHandler(
            LLMConfig(provider="mock", model_name="beta"),
            backend=MockBackend(script=["from beta"], model_name="beta"),
        ),
    }).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"model": "beta",
             "messages": [{"role": "user", "content": "x"}]},
        )
        assert status == 200
        assert json.loads(body)["choices"][0]["message"]["content"] == "from beta"

        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"model": "gamma",
             "messages": [{"role": "user", "content": "x"}]},
        )
        assert status == 404
        assert json.loads(body)["error"]["type"] == "model_not_found"

        # /v1/models lists exactly the servable set.
        status, _, body = await _request(server.port, "GET", "/v1/models")
        ids = [m["id"] for m in json.loads(body)["data"]]
        assert ids == ["alpha", "beta"]

        # Omitted model falls to the default (first) handler.
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}]},
        )
        assert status == 200
        assert json.loads(body)["choices"][0]["message"]["content"] == "from alpha"

        # Per-model metrics in multi-model mode.
        status, _, body = await _request(server.port, "GET", "/metrics")
        assert set(json.loads(body)["handler"]) == {"alpha", "beta"}
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_models_health_metrics():
    server = await APIServer(_mock_handler()).start()
    try:
        status, _, body = await _request(server.port, "GET", "/v1/models")
        assert status == 200
        data = json.loads(body)
        assert data["object"] == "list"
        assert any(m["id"] == "mock-1" for m in data["data"])

        status, _, body = await _request(server.port, "GET", "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"

        status, _, body = await _request(server.port, "GET", "/metrics")
        assert status == 200 and "handler" in json.loads(body)
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_bearer_auth():
    server = await APIServer(_mock_handler(), auth_token="s3cret").start()
    try:
        status, _, _ = await _request(server.port, "GET", "/v1/models")
        assert status == 401
        status, _, _ = await _request(
            server.port, "GET", "/v1/models", token="wrong"
        )
        assert status == 401
        status, _, _ = await _request(
            server.port, "GET", "/v1/models", token="s3cret"
        )
        assert status == 200
        # Liveness stays unauthenticated (probes don't carry secrets).
        status, _, _ = await _request(server.port, "GET", "/healthz")
        assert status == 200
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_error_handling():
    server = await APIServer(_mock_handler()).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            raw_body=b"{not json",
        )
        assert status == 400
        assert json.loads(body)["error"]["type"] == "invalid_request_error"

        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions", {"messages": []}
        )
        assert status == 400

        status, _, _ = await _request(server.port, "GET", "/nope")
        assert status == 404

        # Untrusted client values are 400s, not 500s.
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "temperature": "hot"},
        )
        assert status == 400
        assert json.loads(body)["error"]["type"] == "invalid_request_error"
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}], "seed": "x"},
        )
        assert status == 400

        # OpenAI's content-null assistant turns normalize, not crash.
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [
                {"role": "assistant", "content": None},
                {"role": "user", "content": "hi"},
            ]},
        )
        assert status == 200

        status, _, _ = await _request(server.port, "GET", "/v1/chat/completions")
        assert status == 405

        # No orchestrator attached → 503, not a crash.
        status, _, _ = await _request(
            server.port, "POST", "/v1/tasks", {"task": "do something"}
        )
        assert status == 503
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_task_submission_through_serve():
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, ServeConfig
    from pilottai_tpu.serve import Serve

    llm = _mock_handler()
    agent = BaseAgent(
        config=AgentConfig(role="worker", specializations=["generic"]),
        llm=llm,
    )
    serve = Serve(
        name="api-test", agents=[agent], manager_llm=llm,
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    server = await APIServer(llm, serve=serve).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/tasks",
            {"task": "summarize the quarterly numbers", "timeout": 60},
        )
        assert status == 200
        data = json.loads(body)
        assert data["object"] == "task.result"
        assert data["success"] is True
    finally:
        await server.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_native_engine_over_sse():
    """End to end: the real CPU engine behind the endpoint — SSE deltas
    concatenate to the non-streamed completion for the same request."""
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=2, engine_max_seq=256, engine_chunk=4,
    ))
    server = await APIServer(handler).start()
    try:
        req = {
            "messages": [{"role": "user", "content": "stream this"}],
            "max_tokens": 16, "temperature": 0,
        }
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions", req
        )
        assert status == 200
        full = json.loads(body)["choices"][0]["message"]["content"]

        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {**req, "stream": True},
        )
        assert status == 200
        events = [
            line[len("data: "):]
            for line in body.decode().split("\n")
            if line.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        text = "".join(
            json.loads(e)["choices"][0]["delta"].get("content", "")
            for e in events[:-1]
        )
        assert text == full
    finally:
        await server.stop()
        await handler.stop()


@pytest.mark.asyncio
async def test_embeddings_endpoint():
    from pilottai_tpu.memory.embedder import Embedder

    server = await APIServer(
        _mock_handler(), embedder=Embedder(model_name="llama-tiny")
    ).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/embeddings",
            {"input": ["hello world", "quarterly report"]},
        )
        assert status == 200
        data = json.loads(body)
        assert data["object"] == "list" and len(data["data"]) == 2
        vec = data["data"][0]["embedding"]
        assert len(vec) > 8 and abs(sum(x * x for x in vec) - 1.0) < 1e-3
        # Usage is the encoder's REAL token count (byte tokenizer ≈ one
        # per char), not a chars/4 guess.
        assert data["usage"]["prompt_tokens"] >= len("hello world")

        # Single-string input form.
        status, _, body = await _request(
            server.port, "POST", "/v1/embeddings", {"input": "one text"}
        )
        assert status == 200 and len(json.loads(body)["data"]) == 1

        status, _, _ = await _request(
            server.port, "POST", "/v1/embeddings", {"input": []}
        )
        assert status == 400
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_embeddings_503_without_embedder():
    server = await APIServer(_mock_handler()).start()
    try:
        status, _, _ = await _request(
            server.port, "POST", "/v1/embeddings", {"input": "x"}
        )
        assert status == 503
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_serving_endpoint_example():
    """The examples/serving_endpoint demo runs end to end on mock."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from examples.serving_endpoint.main import main as demo_main

    assert await demo_main("mock", "llama3-1b-byte") == 0


@pytest.mark.asyncio
async def test_json_schema_response_format():
    """response_format json_schema flows to the engine and the output
    validates against the schema by construction (real CPU engine)."""
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=2, engine_max_seq=256, engine_chunk=4,
    ))
    server = await APIServer(handler).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {
                "messages": [{"role": "user", "content": "report status"}],
                "max_tokens": 96, "temperature": 0,
                "response_format": {
                    "type": "json_schema",
                    "json_schema": {
                        "name": "status",
                        "schema": {
                            "type": "object",
                            "properties": {
                                "ok": {"type": "boolean"},
                                "score": {"type": "integer"},
                            },
                            "required": ["ok", "score"],
                        },
                    },
                },
            },
        )
        assert status == 200
        content = json.loads(body)["choices"][0]["message"]["content"]
        data = json.loads(content)
        assert set(data) == {"ok", "score"}
        assert isinstance(data["ok"], bool) and isinstance(data["score"], int)

        # Malformed response_format is a 400, not a 500.
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "response_format": {"type": "json_schema"}},
        )
        assert status == 400
    finally:
        await server.stop()
        await handler.stop()


@pytest.mark.asyncio
async def test_json_mode_response_format():
    server = await APIServer(_mock_handler()).start()
    try:
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {
                "messages": [{"role": "user", "content": "emit json"}],
                "response_format": {"type": "json_object"},
                "max_tokens": 64,
            },
        )
        assert status == 200
        content = json.loads(body)["choices"][0]["message"]["content"]
        json.loads(content)  # mock replies are valid JSON already
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_max_tokens_zero_is_400():
    """Explicit max_tokens: 0 must be rejected, not silently replaced by
    the 256 default (ADVICE r4)."""
    server = await APIServer(_mock_handler()).start()
    try:
        for bad in (0, -3, "many"):
            status, _, body = await _request(
                server.port, "POST", "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "x"}],
                 "max_tokens": bad},
            )
            assert status == 400, body
        # Absent still defaults fine.
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}]},
        )
        assert status == 200
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_strict_json_schema_unenforceable_is_400():
    """strict: true on a deployment that cannot enforce the schema
    (mock backend has no constrained decoding) is a 400 up front —
    OpenAI strict-mode parity (ADVICE r4 medium)."""
    server = await APIServer(_mock_handler()).start()
    try:
        schema = {"type": "object", "properties": {"a": {"type": "integer"}},
                  "required": ["a"]}
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "response_format": {"type": "json_schema",
                                 "json_schema": {"name": "t", "strict": True,
                                                 "schema": schema}}},
        )
        assert status == 400
        assert b"strict" in body
        # Non-strict: best effort is allowed, but the response must say
        # enforcement did NOT happen.
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "response_format": {"type": "json_schema",
                                 "json_schema": {"name": "t",
                                                 "schema": schema}}},
        )
        assert status == 200
        assert json.loads(body)["schema_enforced"] is False
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_strict_json_schema_enforced_on_native_engine():
    """On the byte-tokenizer CPU engine, strict json_schema passes the
    pre-check and the response reports schema_enforced: true."""
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=2, engine_max_seq=256,
    ))
    server = await APIServer(handler).start()
    try:
        schema = {"type": "object",
                  "properties": {"ok": {"type": "boolean"}},
                  "required": ["ok"]}
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "max_tokens": 24,
             "response_format": {"type": "json_schema",
                                 "json_schema": {"name": "t", "strict": True,
                                                 "schema": schema}}},
        )
        assert status == 200, body
        data = json.loads(body)
        assert data["schema_enforced"] is True
        out = json.loads(data["choices"][0]["message"]["content"])
        assert isinstance(out["ok"], bool)
    finally:
        await server.stop()
        await handler.stop()
