"""BaseAgent tests: reasoning loop on the mock engine, hierarchy, tools,
health/suitability surface."""

import asyncio

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.core.task import Task, TaskStatus
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.tools.tool import Tool


def make_agent(**kwargs):
    backend = kwargs.pop("backend", None) or MockBackend()
    handler = LLMHandler(LLMConfig(provider="mock"), backend=backend)
    cfg = kwargs.pop("config", None) or AgentConfig(role="worker")
    return BaseAgent(config=cfg, llm=handler, **kwargs), backend


def test_agent_requires_llm():
    with pytest.raises(ValueError, match="requires an llm"):
        BaseAgent(config=AgentConfig())


@pytest.mark.asyncio
async def test_agent_executes_simple_task():
    agent, backend = make_agent()
    await agent.start()
    assert agent.status == AgentStatus.IDLE
    result = await agent.execute_task(Task(description="compute something"))
    assert result.success
    assert "completed" in str(result.output)
    assert agent.task_metrics["completed"] == 1
    assert agent.status == AgentStatus.IDLE
    # Full protocol ran: analysis, step planning, evaluation.
    joined = "\n".join(backend.calls)
    assert '"understanding"' in joined and '"task_complete"' in joined


@pytest.mark.asyncio
async def test_agent_runs_tool_step():
    calls = []

    def adder(a=0, b=0):
        calls.append((a, b))
        return a + b

    tool = Tool(name="adder", function=adder, description="adds numbers")

    def responder(prompt):
        if '"task_complete"' in prompt:
            if not calls:
                return {"task_complete": False, "action": "adder",
                        "arguments": {"a": 2, "b": 3}, "output": "", "reasoning": ""}
            return {"task_complete": True, "action": "respond", "arguments": {},
                    "output": f"sum={calls[-1]}", "reasoning": ""}
        return None

    backend = MockBackend(responders=[responder])
    agent, _ = make_agent(backend=backend, tools=[tool])
    await agent.start()
    result = await agent.execute_task(Task(description="add 2 and 3", tools=["adder"]))
    assert result.success
    assert calls == [(2, 3)]
    assert "adder" in result.metadata["tools_used"]


@pytest.mark.asyncio
async def test_agent_step_loop_bounded_by_max_iterations():
    backend = MockBackend(steps_to_complete=10**9)  # never completes
    agent, _ = make_agent(
        backend=backend, config=AgentConfig(role="worker", max_iterations=3)
    )
    await agent.start()
    result = await agent.execute_task(Task(description="endless"))
    # Loop must stop after 3 iterations, not hang.
    assert len(result.metadata["steps"]) == 3


@pytest.mark.asyncio
async def test_agent_dependency_validation():
    agent, _ = make_agent()
    await agent.start()
    dep = Task(description="dep")
    registry = {dep.id: dep}
    agent.dependency_resolver = registry.get
    task = Task(description="main", dependencies=[dep.id])
    result = await agent.execute_task(task)
    assert not result.success and "not completed" in result.error
    dep.mark_started()
    dep.mark_completed(__import__("pilottai_tpu").TaskResult(success=True))
    task2 = Task(description="main2", dependencies=[dep.id])
    result2 = await agent.execute_task(task2)
    assert result2.success


@pytest.mark.asyncio
async def test_agent_failure_counts_and_health():
    backend = MockBackend(fail_pattern="poison")
    agent, _ = make_agent(backend=backend)
    agent.llm.config.retries = 0
    await agent.start()
    result = await agent.execute_task(Task(description="poison pill"))
    assert not result.success
    assert agent.task_metrics["failed"] == 1
    health = agent.get_health()
    assert health["error_count"] == 1
    assert agent.success_rate == 0.0


def test_hierarchy_add_remove_and_cycle_guard():
    parent, _ = make_agent()
    child, _ = make_agent()
    grandchild, _ = make_agent()
    parent.add_child_agent(child)
    child.add_child_agent(grandchild)
    assert child.parent is parent
    assert {a.id for a in parent.descendants()} == {child.id, grandchild.id}
    with pytest.raises(ValueError, match="cycle"):
        grandchild.add_child_agent(parent)
    with pytest.raises(ValueError, match="already a child"):
        parent.add_child_agent(child)
    removed = parent.remove_child_agent(child.id)
    assert removed is child and child.parent is None


def test_hierarchy_respects_max_children():
    parent, _ = make_agent(config=AgentConfig(role="m", max_child_agents=1))
    parent.add_child_agent(make_agent()[0])
    with pytest.raises(RuntimeError, match="max_child_agents"):
        parent.add_child_agent(make_agent()[0])


@pytest.mark.asyncio
async def test_suitability_scoring():
    agent, _ = make_agent(
        config=AgentConfig(role="w", specializations=["extract"])
    )
    await agent.start()
    specialized = Task(description="x", type="extract")
    generic = Task(description="x", type="other")
    assert agent.evaluate_task_suitability(specialized) > \
        agent.evaluate_task_suitability(generic)
    missing_caps = Task(description="x", required_capabilities=["gpu_magic"])
    assert agent.evaluate_task_suitability(missing_caps) <= 0.1
    await agent.stop()
    assert agent.evaluate_task_suitability(generic) == 0.0


@pytest.mark.asyncio
async def test_heartbeat_and_queue_surface():
    agent, _ = make_agent()
    await agent.start()
    before = agent._last_heartbeat
    await asyncio.sleep(0.01)
    assert agent.send_heartbeat() > before
    task = Task(description="queued work")
    await agent.add_task(task)
    assert task.status == TaskStatus.QUEUED
    assert agent.queued_tasks() == [task]
    moved = agent.remove_task(task.id)
    assert moved is task and moved.agent_id is None
