"""MoE (expert parallelism) and GPipe pipeline building block."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill
from pilottai_tpu.parallel.mesh import compat_set_mesh, MeshConfig, create_mesh
from pilottai_tpu.parallel.pipeline import pipeline_apply, split_layers_to_stages
from pilottai_tpu.train import Trainer, TrainConfig, synthetic_batches


# ------------------------------- MoE ---------------------------------- #

def test_moe_single_expert_equals_dense():
    """n_experts=1, top-1: routing is a no-op, output must equal the dense
    MLP with identical weights."""
    dense = get_model_config("llama-tiny")
    moe = dense.replace(name="moe1", n_experts=1, n_active_experts=1)
    p_dense = init_params(dense, jax.random.key(0), dtype=jnp.float32)
    p_moe = init_params(moe, jax.random.key(0), dtype=jnp.float32)
    # Copy dense weights into expert 0; attn/norm/embed already match.
    for name in ("wg", "wu", "wd"):
        p_moe["layers"]["moe"][name] = p_dense["layers"]["mlp"][name][:, None]
    p_moe["layers"] = {
        **{k: v for k, v in p_dense["layers"].items() if k != "mlp"},
        "moe": p_moe["layers"]["moe"],
    }
    B, T = 2, 16
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, dense.vocab_size, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    ld, _, _ = forward_prefill(p_dense, dense, tokens, positions, valid)
    lm, _, _ = forward_prefill(p_moe, moe, tokens, positions, valid)
    # einsum vs @ contraction order differs slightly in f32
    np.testing.assert_allclose(ld, lm, atol=1e-4, rtol=1e-4)


def test_moe_trains_with_expert_parallelism():
    cfg = get_model_config("moe-tiny")
    mesh = create_mesh(MeshConfig(data=1, fsdp=2, model=2, seq=2))
    t = Trainer(
        cfg,
        TrainConfig(
            learning_rate=1e-2, warmup_steps=1, total_steps=20,
            context_parallel=True,
        ),
        mesh=mesh,
    )
    state = t.init(jax.random.key(0))
    wg = state[0]["layers"]["moe"]["wg"]
    assert "model" in jax.tree.leaves(
        [wg.sharding.spec]
    )[0] or wg.sharding.spec[1] == "model"  # expert axis on 'model'
    batch = next(synthetic_batches(cfg, 4, 32))
    losses = []
    for _ in range(6):
        state, m = t.step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_registry_param_counts():
    mixtral = get_model_config("mixtral-8x7b")
    assert 45e9 < mixtral.param_count() < 50e9  # 8x7B ≈ 46.7B total
    assert get_model_config("moe-tiny").n_experts == 4


# ----------------------------- pipeline -------------------------------- #

def _mlp_stack(L=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(L, D, D)) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(L, D)) * 0.1, jnp.float32),
    }

    def block_fn(p, x):
        def layer(x, lp):
            return jnp.tanh(x @ lp[0] + lp[1]), None
        x, _ = jax.lax.scan(layer, x, (p["w"], p["b"]))
        return x

    return params, block_fn


@pytest.fixture(scope="module")
def stage_mesh():
    devs = np.asarray(jax.devices()).reshape(4, 2)
    return Mesh(devs, ("stage", "data"))


def test_pipeline_matches_sequential(stage_mesh):
    params, block_fn = _mlp_stack()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 4, 16)), jnp.float32)
    ref = jax.vmap(lambda xi: block_fn(params, xi))(x)
    staged = split_layers_to_stages(params, 4)
    with compat_set_mesh(stage_mesh):
        got = jax.jit(
            lambda p, x: pipeline_apply(
                block_fn, p, x, stage_mesh, batch_axes=("data",)
            )
        )(staged, x)
    np.testing.assert_allclose(ref, got, atol=1e-6)


def test_pipeline_gradients_match(stage_mesh):
    params, block_fn = _mlp_stack()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 4, 16)), jnp.float32)
    staged = split_layers_to_stages(params, 4)

    def loss_seq(params):
        return jnp.sum(jax.vmap(lambda xi: block_fn(params, xi))(x) ** 2)

    def loss_pp(staged):
        return jnp.sum(
            pipeline_apply(block_fn, staged, x, stage_mesh, batch_axes=("data",))
            ** 2
        )

    g_ref = jax.grad(loss_seq)(params)
    with compat_set_mesh(stage_mesh):
        g_pp = jax.jit(jax.grad(loss_pp))(staged)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            g_ref[k].reshape(g_pp[k].shape), g_pp[k], atol=1e-4
        )


def test_pipeline_fewer_microbatches_than_stages(stage_mesh):
    """n_micro < n_stages: pipeline still correct (all-bubble edge case)."""
    params, block_fn = _mlp_stack()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 4, 16)), jnp.float32)
    ref = jax.vmap(lambda xi: block_fn(params, xi))(x)
    staged = split_layers_to_stages(params, 4)
    with compat_set_mesh(stage_mesh):
        got = jax.jit(
            lambda p, x: pipeline_apply(
                block_fn, p, x, stage_mesh, batch_axes=("data",)
            )
        )(staged, x)
    np.testing.assert_allclose(ref, got, atol=1e-6)
