"""ISSUE 14 parity contracts: packed int4 weights and the fused epilogue.

Three pinned invariants:

1. **Packing is lossless**: the packed nibble path (``Q4Tensor`` →
   in-jit unpack+dequant fused into the matmul operand read) produces
   greedy output byte-identical to an *unpacked int4-dequant reference*
   — the same quantized values pre-expanded to dense arrays — across
   dense/paged caches × speculation on/off. Quantization error is the
   scheme's; the packed representation adds NONE.
2. **The fused greedy epilogue changes nothing**: projection+argmax
   fused per vocab tile (``engine_fused_epilogue``) is byte-identical
   to the unfused sampler, across the same matrix, including mixed
   batches where a sampled or JSON slot forces the unfused dispatch.
3. **The native quantized-operand lowering carries no dense fp32
   weight** (HLO inspector, the PR 12 ``collective_ops`` pattern
   applied to buffer dtypes/shapes).

Byte-identity runs against the fused-dequant qmatmul arm (the CPU
default); the native integer-operand arm intentionally requantizes
activations, so it is covered by the HLO inspector + a quality smoke
against the committed protocol-s checkpoint instead.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.quant import (
    Q4Tensor,
    QTensor,
    dequant,
    pack_int4,
    quantize_array,
    quantize_params,
    unpack_int4,
    weight_stream_bytes,
)
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill


# --------------------------------------------------------------------- #
# Fast: pack/unpack + quantize units
# --------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "in_dim,out_dim", [(8, 4), (7, 5), (1, 3), (63, 8)],
    ids=["even", "odd", "single-row", "odd-63"],
)
def test_pack_unpack_roundtrip(in_dim, out_dim):
    """Nibble packing round-trips every int4 value, including the odd
    trailing row that shares its byte with a zero pad nibble."""
    rng = np.random.default_rng(in_dim * 31 + out_dim)
    q = jnp.asarray(rng.integers(-8, 8, (in_dim, out_dim)), jnp.int8)
    packed = pack_int4(q)
    assert packed.shape == (-(-in_dim // 2), out_dim)
    assert packed.dtype == jnp.int8
    back = unpack_int4(packed, in_dim)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(q))


def test_pack_unpack_extremes_stacked():
    """-8 and +7 survive packing in both nibble positions, with leading
    stack axes (the stacked-layer layout)."""
    q = jnp.asarray(
        np.tile(np.array([[-8], [7], [-1], [0], [3]], np.int8), (2, 1, 1, 4))
    ).astype(jnp.int8)                                   # [2, 5, 4]
    back = unpack_int4(pack_int4(q), 5)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(q))


@pytest.mark.parametrize(
    "in_dim,group", [(64, 16), (40, 16), (7, 3), (10, 128)],
    ids=["dividing", "remainder", "odd-remainder", "one-group"],
)
def test_quantize4_roundtrip_error_bounded(in_dim, group):
    """Per-group int4: worst-case error is half a step of the GROUP's
    own scale (amax/14), and the remainder group's scale reflects only
    its real rows (zero padding must not inflate it)."""
    rng = np.random.default_rng(in_dim + group)
    w = jnp.asarray(rng.normal(size=(in_dim, 6)) * 0.05, jnp.float32)
    t = quantize_array(w, jnp.float32, bits=4, group=group)
    n_groups = -(-in_dim // group)
    assert t.s.shape == (n_groups, 6)
    assert t.q.shape == (-(-in_dim // 2), 6)
    back = np.asarray(dequant(t))
    wn = np.asarray(w)
    for g in range(n_groups):
        rows = slice(g * group, min((g + 1) * group, in_dim))
        amax = np.abs(wn[rows]).max(axis=0)
        bound = amax / 14 + 1e-6
        assert (np.abs(back[rows] - wn[rows]) <= bound[None, :]).all()


def test_quantize_params_int4_fallback_leaves():
    """bits=4 leaf selection: layer matmuls pack to Q4Tensor, lm_head
    falls back to int8 (argmax-sensitive), the MoE router stays dense
    (expert-selection-sensitive), norms/embeds stay dense."""
    cfg = get_model_config("llama-tiny").replace(tie_embeddings=False)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params, dtype=jnp.float32, bits=4, group=32)
    lp = qp["layers"]
    assert isinstance(lp["attn"]["wq"], Q4Tensor)
    assert isinstance(lp["mlp"]["wd"], Q4Tensor)
    assert isinstance(qp["lm_head"], QTensor)          # int8 fallback
    assert not isinstance(lp["ln1"]["scale"], (QTensor, Q4Tensor))
    assert not isinstance(qp["embed"], (QTensor, Q4Tensor))

    moe = get_model_config("moe-tiny")
    mp = init_params(moe, jax.random.PRNGKey(0), dtype=jnp.float32)
    mq = quantize_params(mp, dtype=jnp.float32, bits=4, group=32)
    assert isinstance(mq["layers"]["moe"]["wg"], Q4Tensor)
    assert not isinstance(
        mq["layers"]["moe"]["router"], (QTensor, Q4Tensor)
    )


def test_quantize_params_int4_from_int8_tree():
    """The eager-init / checkpoint path hands quantize_params an
    already-int8 tree; bits=4 requantizes it (deterministically) rather
    than nesting quantized types."""
    cfg = get_model_config("llama-tiny")
    q8 = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32,
                     quantize=True)
    q4 = quantize_params(q8, dtype=jnp.float32, bits=4, group=32)
    wq = q4["layers"]["attn"]["wq"]
    assert isinstance(wq, Q4Tensor)
    assert not isinstance(wq.q, (QTensor, Q4Tensor))


def test_weight_stream_bytes_int4_halves_layer_stream():
    """The measured gauge inputs: int4 layer bytes land at or under
    0.55x of int8 (the acceptance ratio the 8B QUANT section asserts on
    the accel path — layer-only here, because a tiny tied vocab makes
    the dense embed a far larger share than it is at 8B)."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    layers8 = weight_stream_bytes(
        {"layers": quantize_params(params, dtype=jnp.float32)["layers"]}
    )["total"]
    layers4 = weight_stream_bytes(
        {"layers": quantize_params(
            params, dtype=jnp.float32, bits=4, group=128
        )["layers"]}
    )["total"]
    assert layers4 <= 0.55 * layers8, (layers4, layers8)
    full = weight_stream_bytes(params)
    assert full["per_token"] <= full["total"]


def test_forward_packed_matches_unpacked_reference():
    """Prefill logits byte-identical: packed Q4 params vs the same
    quantized values pre-expanded to dense arrays."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    q4 = quantize_params(params, dtype=jnp.float32, bits=4, group=32)
    ref = _dequant_tree(q4)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(2, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16)).astype(jnp.int32)
    valid = jnp.full((2,), 16, jnp.int32)
    l4, _, _ = forward_prefill(q4, cfg, tokens, pos, valid, use_flash=False)
    lr, _, _ = forward_prefill(ref, cfg, tokens, pos, valid, use_flash=False)
    np.testing.assert_array_equal(np.asarray(l4), np.asarray(lr))


def test_fused_epilogue_multi_tile_carry():
    """The cross-tile (max, argmax) carry — which production vocabs
    (128K+) exercise but CI models (vocab ≤ 512) never reach at the
    default 8192 tile — must reproduce ``jnp.argmax`` over the full
    projection exactly, including ties AT tile boundaries (lowest index
    wins) and heads in every representation."""
    from pilottai_tpu.engine.decode import fused_greedy_epilogue
    from pilottai_tpu.models.transformer import _unembed

    cfg = get_model_config("llama-tiny").replace(dtype=jnp.float32)
    rng = np.random.default_rng(11)
    V, E, B = cfg.vocab_size, cfg.hidden_size, 3
    h = jnp.asarray(rng.normal(size=(B, 2, E)) * 0.1, jnp.float32)

    def check(params):
        ref = jnp.argmax(_unembed(cfg, params, h), axis=-1).astype(jnp.int32)
        for tile in (64, 100, V, 4 * V):  # many tiles / ragged / 1 / over
            got = jax.jit(
                lambda hh, p: fused_greedy_epilogue(cfg, p, hh, tile=tile)
            )(h, params)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    # Tied head (embed.T), plain untied head, int8 head, int4 head.
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    # Exact ties straddling a tile-64 boundary: duplicate embed rows
    # 63/64 and 127/130 — argmax must pick the lower index either way.
    embed = np.array(params["embed"])  # writable copy
    embed[64] = embed[63]
    embed[130] = embed[127]
    params["embed"] = jnp.asarray(embed)
    check(params)
    untied = get_model_config("llama-tiny").replace(
        dtype=jnp.float32, tie_embeddings=False
    )
    uparams = init_params(untied, jax.random.PRNGKey(3), dtype=jnp.float32)
    check(uparams)
    check({**uparams, "lm_head": quantize_array(
        uparams["lm_head"], jnp.float32
    )})
    check({**uparams, "lm_head": quantize_array(
        uparams["lm_head"], jnp.float32, bits=4, group=32
    )})


def test_autotune_key_includes_quant_mode():
    """ISSUE 14 satellite regression: the page-strip autotune key must
    invalidate across weight-quant mode AND group changes (a winner
    timed under bf16 was silently reused under int4); 'none' keeps the
    pre-existing key so old cache entries stay valid."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    def keys(**kw):
        b = ContinuousBatcher(
            cfg, params, n_slots=2, max_seq_len=64, chunk_size=4,
            cache_dtype=jnp.float32, paged=True, page_size=16,
            use_pallas=False, **kw,
        )
        return b._strip_autotune_keys()

    base = keys()
    assert ":wq" not in base[0] and ":wq" not in base[1]
    k8 = keys(weight_quant="int8")
    k4 = keys(weight_quant="int4")
    k4g = keys(weight_quant="int4", quant_group=64)
    assert len({base[0], k8[0], k4[0], k4g[0]}) == 4
    assert len({base[1], k8[1], k4[1], k4g[1]}) == 4


def test_qmatmul_native_hlo_no_dense_fp32_weight(monkeypatch):
    """HLO inspector (the PR 12 pattern pointed at operand buffers): the
    native quantized-operand lowering must contain an integer dot and NO
    weight-shaped fp32/bf16 buffer — the whole point is that the dense
    copy never exists in HBM."""
    monkeypatch.setenv("PILOTTAI_QMATMUL", "native")
    from pilottai_tpu.models.qmatmul import qmatmul

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(96, 112)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 96)), jnp.float32)
    for bits, group in ((8, 128), (4, 32)):
        qt = quantize_array(w, jnp.float32, bits=bits, group=group)
        hlo = (
            jax.jit(lambda a, t: qmatmul(a, t))
            .lower(x, qt).compile().as_text()
        )
        for banned in ("f32[96,112]", "bf16[96,112]", "f16[96,112]"):
            assert banned not in hlo, (bits, banned)
        assert "s8[" in hlo, bits
        assert "s32[" in hlo, bits  # integer accumulation


def test_qmatmul_native_close_to_dequant(monkeypatch):
    """The integer-operand arm is a different rounding of the same
    matmul: relative error vs the fused-dequant arm stays at 8-bit
    activation-quantization scale."""
    from pilottai_tpu.models import qmatmul as qm

    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(64, 48)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.normal(size=(5, 64)), jnp.float32)
    for bits in (8, 4):
        qt = quantize_array(w, jnp.float32, bits=bits, group=16)
        monkeypatch.setenv("PILOTTAI_QMATMUL", "dequant")
        ref = np.asarray(qm.qmatmul(x, qt))
        monkeypatch.setenv("PILOTTAI_QMATMUL", "native")
        nat = np.asarray(qm.qmatmul(x, qt))
        denom = np.abs(ref).mean() + 1e-6
        assert np.abs(nat - ref).mean() / denom < 0.02, bits


def test_quant_quality_smoke_protocol_checkpoint():
    """End-to-end quality smoke on the committed protocol-s checkpoint:
    int4 logits track the full-precision forward (high correlation,
    dominant greedy agreement). Guards against a quantizer bug that
    byte-identity tests cannot see (they compare the quantized path to
    itself)."""
    from pilottai_tpu.models.loader import load_checkpoint
    from pilottai_tpu.train.protocol import DEFAULT_CHECKPOINT

    cfg = get_model_config("protocol-s").replace(dtype=jnp.float32)
    params = load_checkpoint(
        cfg, str(DEFAULT_CHECKPOINT), dtype=jnp.float32
    )
    q4 = quantize_params(params, dtype=jnp.float32, bits=4, group=128)
    text = b"[task] extract: the quick brown fox jumps over the lazy dog"
    ids = jnp.asarray(np.frombuffer(text, np.uint8).astype(np.int32) + 3)[None]
    T = ids.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T)[None], (1, T)).astype(jnp.int32)
    valid = jnp.full((1,), T, jnp.int32)
    ref = _dequant_tree(q4)
    lq, _, _ = forward_prefill(q4, cfg, ids, pos, valid, use_flash=False)
    lr, _, _ = forward_prefill(ref, cfg, ids, pos, valid, use_flash=False)
    np.testing.assert_array_equal(np.asarray(lq), np.asarray(lr))
    # Quality vs the full-precision checkpoint forward.
    lf, _, _ = forward_prefill(params, cfg, ids, pos, valid,
                               use_flash=False)
    lf, lq = np.asarray(lf), np.asarray(lq)
    corr = np.corrcoef(lf.ravel(), lq.ravel())[0, 1]
    assert corr > 0.97, corr
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree > 0.8, agree


# --------------------------------------------------------------------- #
# Slow: engine byte-identity matrices (the CI quant lane owns these)
# --------------------------------------------------------------------- #


def _dequant_tree(tree):
    """Expand every quantized leaf to its exact dense dequant — the
    'unpacked reference' side of the parity contract."""
    return jax.tree.map(
        lambda a: dequant(a) if isinstance(a, (QTensor, Q4Tensor)) else a,
        tree,
        is_leaf=lambda x: isinstance(x, (QTensor, Q4Tensor)),
    )


PROMPT_SETS = [
    [(i * 7 + 3) % 500 + 2 for i in range(41)],
    [(i * 13 + 11) % 500 + 2 for i in range(23)],
    [(i * 3 + 29) % 500 + 2 for i in range(67)],
]


def _run_engine(params, *, paged, speculate, fused=True, max_new=12,
                requests=None):
    cfg = get_model_config("llama-tiny")
    kwargs = dict(
        n_slots=2, max_seq_len=128, cache_dtype=jnp.float32, chunk_size=4,
        use_pallas=False, speculate=speculate, fused_epilogue=fused,
    )
    if paged:
        kwargs.update(paged=True, page_size=16)
    b = ContinuousBatcher(cfg, params, **kwargs)
    b.start()
    try:
        reqs = requests or [
            GenRequest(prompt_ids=list(p), max_new_tokens=max_new)
            for p in PROMPT_SETS
        ]
        futs = [b.submit(r) for r in reqs]
        return [f.result(timeout=600) for f in futs]
    finally:
        b.stop()


@pytest.mark.slow
@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 4), (True, 0), (True, 4)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
def test_packed_int4_engine_byte_identity(paged, speculate):
    """The ISSUE 14 parity contract, end to end: greedy engine output
    byte-identical between the packed-int4 path and the unpacked
    int4-dequant reference, across dense/paged × spec on/off."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    q4 = quantize_params(params, dtype=jnp.float32, bits=4, group=32)
    ref = _dequant_tree(q4)
    out_packed = _run_engine(q4, paged=paged, speculate=speculate)
    out_ref = _run_engine(ref, paged=paged, speculate=speculate)
    assert out_packed == out_ref
    assert any(out_packed)  # non-vacuous


@pytest.mark.slow
@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 4), (True, 0), (True, 4)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
@pytest.mark.parametrize("quant", ["none", "int4"], ids=["bf", "int4"])
def test_fused_epilogue_byte_identity(paged, speculate, quant):
    """Fused vs unfused epilogue, byte-identical across dense/paged ×
    spec on/off, on dense AND int4-packed weights."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    if quant == "int4":
        params_run = quantize_params(
            params, dtype=jnp.float32, bits=4, group=32
        )
    else:
        params_run = params
    out_fused = _run_engine(params_run, paged=paged, speculate=speculate,
                            fused=True)
    # Donated trees: rebuild identical params for the second engine.
    params2 = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    if quant == "int4":
        params2 = quantize_params(params2, dtype=jnp.float32, bits=4,
                                  group=32)
    out_plain = _run_engine(params2, paged=paged, speculate=speculate,
                            fused=False)
    assert out_fused == out_plain
    assert any(out_fused)


@pytest.mark.slow
def test_fused_epilogue_mixed_batch_falls_back():
    """A sampled slot in the batch forces the unfused dispatch: with the
    knob ON, output equals the knob-OFF run for the same seeds — the
    sampled request's PRNG trajectory must be untouched by fusion."""
    cfg = get_model_config("llama-tiny")

    def reqs():
        return [
            GenRequest(prompt_ids=PROMPT_SETS[0][:], max_new_tokens=10),
            GenRequest(
                prompt_ids=PROMPT_SETS[1][:], max_new_tokens=10,
                temperature=0.9, seed=7,
            ),
        ]

    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    out_on = _run_engine(params, paged=False, speculate=0, fused=True,
                         requests=reqs())
    params2 = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    out_off = _run_engine(params2, paged=False, speculate=0, fused=False,
                          requests=reqs())
    assert out_on == out_off


@pytest.mark.slow
def test_fused_epilogue_json_slot_falls_back():
    """Byte-tokenizer JSON constraint rides NO tables (the built-in
    byte automaton), so the fused gate must check the REQUESTS, not the
    riding tables: a greedy json_mode slot forces the unfused dispatch
    and output equals the knob-off run (regression for the
    chunk_json-is-None gate bug)."""
    cfg = get_model_config("llama-tiny")

    def reqs():
        return [
            GenRequest(
                prompt_ids=PROMPT_SETS[0][:], max_new_tokens=10,
                json_mode=True,
            ),
            GenRequest(prompt_ids=PROMPT_SETS[1][:], max_new_tokens=10),
        ]

    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    out_on = _run_engine(params, paged=False, speculate=0, fused=True,
                         requests=reqs())
    params2 = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    out_off = _run_engine(params2, paged=False, speculate=0, fused=False,
                          requests=reqs())
    assert out_on == out_off
    # Non-vacuous: the constrained slot's ids must be byte-range (the
    # automaton actually masked) — an unmasked argmax over a 512 vocab
    # would sooner or later emit >255.
    assert all(t < 256 for t in out_on[0])


@pytest.mark.slow
def test_engine_serves_int4_e2e():
    """LLMHandler smoke through engine_quant='int4' + fused epilogue
    (the knob path, not just direct batcher construction)."""
    import asyncio

    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.utils.metrics import global_metrics

    async def main():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=64, engine_chunk=4, dtype="float32",
            engine_quant="int4", engine_quant_group=64,
        ))
        out = await h.apredict(
            "hello world", params=GenerationParams(max_new_tokens=6)
        )
        metrics = h.get_metrics()
        await h.stop()
        return out, metrics

    out, metrics = asyncio.run(main())
    assert isinstance(out, str) and len(out) > 0
    quant = metrics["backend"]["quant"]
    assert quant["weight_quant"] == "int4"
    assert quant["quant_group"] == 64
    assert quant["weight_bytes_per_token"] > 0
    assert global_metrics.get("engine.weight_bytes") > 0
