"""Grammar-constrained JSON decoding (engine/json_mask.py).

SURVEY.md §7 hard part #3 / VERDICT r1 next-step #4: with random weights
every free-form generation is garbage; under the byte-level grammar mask
every generation must parse. These tests drive the real cpu-provider
engine, not the mock.
"""

import asyncio
import json

import numpy as np
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.json_mask import (
    ALLOWED_NP,
    DDEPTH_NP,
    MAX_DEPTH,
    NEXT_NP,
    S_DONE,
    S_START,
    _OPENERS_NP,
)
from pilottai_tpu.engine.types import GenerationParams


def _host_walk(rng, max_steps=300):
    """Reference host-side walk of the table automaton."""
    state, stack, depth = S_START, 0, 0
    out = []
    for _ in range(max_steps):
        if state == S_DONE:
            return bytes(out), True
        top = (stack >> max(depth - 1, 0)) & 1 if depth > 0 else 0
        mask = ALLOWED_NP[state, top].copy()
        if depth >= MAX_DEPTH:
            mask &= ~_OPENERS_NP
        choices = np.flatnonzero(mask)
        assert len(choices), f"dead end in state {state}"
        weights = np.where(np.isin(choices, [125, 93]), 10.0, 1.0)
        weights = np.where(np.isin(choices, [123, 91]), 0.3, weights)
        b = int(rng.choice(choices, p=weights / weights.sum()))
        out.append(b)
        ns = int(NEXT_NP[state, top, b])
        dd = int(DDEPTH_NP[state, top, b])
        if dd > 0:
            stack |= (1 if b == 91 else 0) << depth
        depth = max(depth + dd, 0)
        if dd < 0 and depth == 0:
            ns = S_DONE
        state = ns
    return bytes(out), False


def test_automaton_random_walks_always_valid_json():
    rng = np.random.default_rng(7)
    closed = 0
    for _ in range(500):
        doc, done = _host_walk(rng)
        if done:
            json.loads(doc.decode("utf-8"))  # must not raise
            closed += 1
    assert closed > 400  # the closer bias terminates almost every walk


def test_device_mask_and_advance_match_tables():
    import jax.numpy as jnp

    from pilottai_tpu.engine.json_mask import json_advance, json_allowed_bytes

    rng = np.random.default_rng(3)
    state = jnp.asarray([S_START], jnp.int32)
    stack = jnp.asarray([0], jnp.int32)
    depth = jnp.asarray([0], jnp.int32)
    h_state, h_stack, h_depth = S_START, 0, 0
    for _ in range(120):
        if h_state == S_DONE:
            break
        mask = np.asarray(json_allowed_bytes(state, stack, depth))[0]
        top = (h_stack >> max(h_depth - 1, 0)) & 1 if h_depth > 0 else 0
        np.testing.assert_array_equal(mask, ALLOWED_NP[h_state, top])
        b = int(rng.choice(np.flatnonzero(mask)))
        state, stack, depth = json_advance(
            state, stack, depth, jnp.asarray([b], jnp.int32)
        )
        ns = int(NEXT_NP[h_state, top, b])
        dd = int(DDEPTH_NP[h_state, top, b])
        if dd > 0:
            h_stack |= (1 if b == 91 else 0) << h_depth
        h_depth = max(h_depth + dd, 0)
        if dd < 0 and h_depth == 0:
            ns = S_DONE
        h_state = ns
        assert int(state[0]) == h_state and int(depth[0]) == h_depth


@pytest.mark.asyncio
async def test_cpu_engine_json_mode_always_parseable():
    """Random-weight model + grammar mask => every reply parses. This is
    the end-to-end contract the agent protocol relies on."""
    handler = LLMHandler(
        LLMConfig(
            model_name="llama-tiny", provider="cpu",
            engine_max_seq=256, engine_slots=4,
        )
    )
    try:
        params = GenerationParams(
            max_new_tokens=120, temperature=1.0, json_mode=True
        )
        outs = await asyncio.gather(*[
            handler.apredict(f"Respond with JSON. Case {i}.", params=params)
            for i in range(8)
        ])
        for text in outs:
            # Forced closure guarantees EVERY reply is a complete document
            # within budget (json_mask margin invariant).
            t = text.strip()
            assert t.startswith(("{", "[")), f"non-JSON start: {t[:40]!r}"
            doc = json.loads(t)
            assert isinstance(doc, (dict, list))
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_json_mode_respects_free_slots_in_same_batch():
    """json and non-json requests share one decode batch; masking one slot
    must not constrain the other."""
    handler = LLMHandler(
        LLMConfig(
            model_name="llama-tiny", provider="cpu",
            engine_max_seq=256, engine_slots=4,
        )
    )
    try:
        j, free = await asyncio.gather(
            handler.apredict(
                "json",
                params=GenerationParams(
                    max_new_tokens=80, temperature=1.0, json_mode=True
                ),
            ),
            handler.apredict(
                "free",
                params=GenerationParams(
                    max_new_tokens=40, temperature=30.0, seed=5
                ),
            ),
        )
        assert j.strip().startswith(("{", "["))
        assert len(free) > 0
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_forced_closure_tiny_budgets_always_parse():
    """Adversarial budgets: even 3-12 token budgets must yield complete
    documents (the forced-closure margin invariant; a review finding
    showed +2 margin could truncate '{\"\":0')."""
    handler = LLMHandler(
        LLMConfig(
            model_name="llama-tiny", provider="cpu",
            engine_max_seq=128, engine_slots=4,
        )
    )
    try:
        outs = await asyncio.gather(*[
            handler.apredict(
                f"budget case {n}",
                params=GenerationParams(
                    max_new_tokens=n, temperature=1.0, seed=n, json_mode=True
                ),
            )
            for n in range(3, 13)
        ])
        for n, text in zip(range(3, 13), outs):
            doc = json.loads(text.strip())
            assert isinstance(doc, (dict, list)), (n, text)
    finally:
        await handler.stop()
