"""Model correctness: prefill/decode agreement, family behaviors, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.models.common import init_params, param_logical_axes
from pilottai_tpu.models.gemma import GEMMA_TINY
from pilottai_tpu.models.llama import LLAMA_TINY
from pilottai_tpu.models.registry import get_model_config, list_models
from pilottai_tpu.models.transformer import forward_decode, forward_prefill
from pilottai_tpu.ops.kvcache import KVCache, write_prompts
from pilottai_tpu.engine.sampling import SamplingState, sample_tokens, update_slot


def _prefill_then_decode_logits(cfg, tokens_list):
    """Reference check: full prefill over [t0..tn] must agree with
    prefill([t0..tk]) + decode steps for the rest."""
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    T = len(tokens_list)
    tokens = jnp.asarray(tokens_list)[None, :]
    positions = jnp.arange(T)[None, :]
    valid = jnp.asarray([T])

    full_logits, _, _ = forward_prefill(params, cfg, tokens, positions, valid)

    # Now: prefill the first half, decode the second half token by token.
    half = T // 2
    p_tokens = jnp.zeros((1, T), jnp.int32).at[0, :half].set(tokens[0, :half])
    p_logits, ks, vs = forward_prefill(
        params, cfg, p_tokens, positions, jnp.asarray([half])
    )
    cache = KVCache.create(cfg.n_layers, 2, T, cfg.n_kv_heads, cfg.head_dim,
                           dtype=jnp.float32)
    cache = write_prompts(
        cache, jnp.asarray([0]), ks[:, :1], vs[:, :1], jnp.asarray([half])
    )

    active = jnp.asarray([True, False])
    decode_logits = []
    for i in range(half, T):
        step_tokens = jnp.asarray([tokens_list[i], 0], jnp.int32)
        logits, cache = forward_decode(params, cfg, step_tokens, cache, active)
        decode_logits.append(logits[0])
    return full_logits[0], decode_logits, half


@pytest.mark.parametrize("cfg_name", ["llama-tiny", "gemma-tiny"])
def test_decode_matches_prefill(cfg_name):
    cfg = get_model_config(cfg_name)
    tokens = list(np.random.RandomState(0).randint(0, cfg.vocab_size, size=8))
    full, decoded, half = _prefill_then_decode_logits(cfg, tokens)
    for i, step_logits in enumerate(decoded):
        np.testing.assert_allclose(
            np.asarray(full[half + i]), np.asarray(step_logits),
            rtol=2e-4, atol=2e-4,
        )


def test_param_count_matches_tree():
    for name in ("llama-tiny", "gemma-tiny"):
        cfg = get_model_config(name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        total = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert total == cfg.param_count(), name


def test_logical_axes_tree_matches_params():
    cfg = LLAMA_TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    axes = param_logical_axes(cfg)
    p_struct = jax.tree.structure(params)
    a_struct = jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert p_struct == a_struct


def test_gemma_softcap_bounds_logits():
    cfg = GEMMA_TINY
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.ones((1, 4), jnp.int32)
    logits, _, _ = forward_prefill(
        params, cfg, tokens, jnp.arange(4)[None], jnp.asarray([4])
    )
    assert float(jnp.max(jnp.abs(logits))) <= cfg.logit_softcap + 1e-3


def test_registry_lists_flagship_models():
    names = list_models()
    assert "llama3-8b" in names and "gemma-2b" in names
    cfg = get_model_config("llama3-8b")
    assert cfg.param_count() > 7_000_000_000


def test_sampling_greedy_and_temperature():
    logits = jnp.asarray([[0.0, 5.0, 1.0], [3.0, 0.0, 0.0]], jnp.float32)
    state = SamplingState.create(2, seed=0)
    tokens, state = sample_tokens(logits, state)
    assert tokens.tolist() == [1, 0]  # temperature 0 -> greedy
    # High temperature + top_k=1 still forces the argmax.
    state = update_slot(state, 0, temperature=2.0, top_k=1, top_p=1.0, seed=7)
    tokens2, _ = sample_tokens(logits, state)
    assert int(tokens2[0]) == 1


def test_sampling_top_p_restricts_support():
    # One dominant token (prob ~0.88): top_p=0.5 must always pick it.
    logits = jnp.tile(jnp.asarray([[4.0, 2.0, 0.0, -1.0]]), (1, 1))
    state = SamplingState.create(1, seed=1)
    state = update_slot(state, 0, temperature=1.0, top_k=0, top_p=0.5, seed=3)
    for _ in range(20):
        tok, state = sample_tokens(logits, state)
        assert int(tok[0]) == 0
