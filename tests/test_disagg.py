"""Disaggregated prefill/decode serving (distributed/cell.py, ISSUE 19).

Contracts pinned here:

* ``cell_disagg`` parses ``"<P>p<D>d"`` (config validator and cell
  parser share the grammar); unset is an EXACT no-op — every replica
  stays ``mixed``, every handoff counter stays zero and the colocated
  cell behaves as before;
* the router's tier filter restricts candidates to ``tier`` + ``mixed``
  and degrades to the full candidate set when a tier is empty —
  disaggregation never sheds where colocation would serve;
* sticky-prefix affinity wins ties BEFORE the headroom/queue terms get
  a vote (the BENCH_r07 ``cell_affinity_hit_rate == 0.29`` bug): only a
  queue gap past ``affinity_tie_margin`` overrides locality;
* greedy output across prefill→handoff→decode is byte-identical to the
  colocated single-engine run, across dense/paged × spec on/off ×
  int8/int4 quantization, and the decode replica RESTORED the handed-off
  KV instead of re-prefilling;
* a corrupted handoff frame is rejected by the integrity framing and the
  request falls back colocated, still byte-identical;
* a prefill replica killed mid-handoff falls back colocated with
  identical output (recovered_frac == 1.0), and once health marks it
  unroutable the cell serves on without the prefill tier.
"""

import asyncio

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.distributed import (
    CellOverloaded,
    CellReplica,
    ReplicaRouter,
    ReplicaSignals,
    RoutingTable,
    ServingCell,
    parse_disagg_spec,
)
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.reliability import global_engine_health
from pilottai_tpu.utils.metrics import global_metrics


# --------------------------------------------------------------------- #
# Spec parsing + config knob
# --------------------------------------------------------------------- #

def test_parse_disagg_spec():
    assert parse_disagg_spec("1p2d") == (1, 2)
    assert parse_disagg_spec("2P+1D") == (2, 1)
    assert parse_disagg_spec("  3p3d ") == (3, 3)
    for bad in ("", "pd", "1p", "2d", "p2d", "1p2d3x", "one-p-two-d"):
        with pytest.raises(ValueError):
            parse_disagg_spec(bad)


def test_config_knob_validates_and_normalizes():
    assert LLMConfig(cell_disagg="1p2d").cell_disagg == "1p2d"
    assert LLMConfig(cell_disagg="2P+1D").cell_disagg == "2p+1d"
    assert LLMConfig().cell_disagg is None
    with pytest.raises(Exception):
        LLMConfig(cell_disagg="two-p-one-d")


# --------------------------------------------------------------------- #
# Router: tier signal + tier filter
# --------------------------------------------------------------------- #

def _sig(rid, **kw):
    return ReplicaSignals(replica_id=rid, **kw)


def test_signals_tier_payload_roundtrip():
    s = _sig("a", tier="prefill")
    back = ReplicaSignals.from_payload(s.to_payload())
    assert back.tier == "prefill"
    # Old heartbeat payloads (no tier key) default to "mixed".
    legacy = s.to_payload()
    del legacy["tier"]
    assert ReplicaSignals.from_payload(legacy).tier == "mixed"


def test_pick_tier_filter():
    r = ReplicaRouter()
    sigs = [
        _sig("p0", tier="prefill"),
        _sig("d0", tier="decode"),
        _sig("m0", tier="mixed"),
    ]
    for _ in range(8):
        rid, _ = r.pick((1, 2, 3), sigs, tier="prefill")
        assert rid in ("p0", "m0")
    for _ in range(8):
        rid, _ = r.pick((4, 5, 6), sigs, tier="decode")
        assert rid in ("d0", "m0")


def test_pick_empty_tier_falls_back_to_all_candidates():
    r = ReplicaRouter()
    sigs = [_sig("p0", tier="prefill"), _sig("p1", tier="prefill")]
    # No decode or mixed replica: the tier filter must degrade to the
    # colocated policy, not shed.
    rid, _ = r.pick((1, 2, 3), sigs, tier="decode")
    assert rid in ("p0", "p1")
    # ...but unroutable replicas still shed as before.
    dead = [_sig("p0", tier="prefill", healthy=False)]
    with pytest.raises(CellOverloaded):
        r.pick((1, 2, 3), dead, tier="decode")


def test_affinity_wins_ties_within_margin():
    """The BENCH_r07 bug: one extra in-flight request (queue_frac
    0.125 at the default soft_inflight 8) must NOT steal a warm prefix
    from its owner. Only a gap past ``affinity_tie_margin`` may."""
    table = RoutingTable()
    key = tuple(range(100, 140))
    table.note(key[:4], "a")  # shallow hit: affinity fraction 0.1
    r = ReplicaRouter(table)
    busy_owner = [
        _sig("a", queue_frac=0.125),  # one in-flight request ahead
        _sig("b", queue_frac=0.0),
    ]
    for _ in range(6):
        rid, lcp = r.pick(key, busy_owner)
        assert (rid, lcp) == ("a", 4)
    # A real load gap (past the margin) still overrides locality.
    swamped_owner = [
        _sig("a", queue_frac=1.5),
        _sig("b", queue_frac=0.0),
    ]
    rid, lcp = r.pick(key, swamped_owner)
    assert (rid, lcp) == ("b", 0)


# --------------------------------------------------------------------- #
# Cell topology (mock provider)
# --------------------------------------------------------------------- #

def _mock_cell(n=3, **kw):
    reps = [
        CellReplica(f"r{i}", LLMHandler(LLMConfig(provider="mock")))
        for i in range(n)
    ]
    return ServingCell(reps, **kw)


_HANDOFF_COUNTERS = (
    "cell.handoffs",
    "cell.handoff_fallbacks",
    "cell.handoff_rejected",
    "cell.handoff_tokens",
    "cell.tier.prefill_routed",
    "cell.tier.decode_routed",
    "cell.tier.bypass",
)


def _counters():
    return {name: global_metrics.get(name) for name in _HANDOFF_COUNTERS}


@pytest.mark.asyncio
async def test_colocated_cell_is_exact_noop():
    """No ``cell_disagg`` → no tiers, no handoff counters, no disagg
    branches: the colocated cell must be indistinguishable from PR 11."""
    before = _counters()
    cell = _mock_cell()
    await cell.start()
    try:
        assert not cell._disagg
        assert all(s.tier == "mixed" for s in cell.signals())
        for i in range(4):
            out = await cell.apredict(
                "please analyze the fleet report, section %d" % i
            )
            assert out
        snap = cell.health_snapshot()
        assert set(snap["tiers"].values()) == {"mixed"}
    finally:
        await cell.stop()
    assert _counters() == before


@pytest.mark.asyncio
async def test_disagg_tiers_assigned_and_mock_backend_serves_colocated():
    """``cell_disagg`` splits replicas into tiers; a backend without the
    handoff surface (mock) early-outs BEFORE committing a handoff and
    the request is served colocated — no counter moves, no error."""
    before = _counters()
    cell = _mock_cell(3, cell_disagg="1p2d")
    await cell.start()
    try:
        assert cell._disagg
        tiers = [cell.replicas[r].tier for r in sorted(cell.replicas)]
        assert tiers == ["prefill", "decode", "decode"]
        snap = cell.health_snapshot()
        assert sorted(snap["tiers"].values()) == ["decode", "decode", "prefill"]
        out = await cell.apredict(
            "a cold prompt long enough to clear the minimum key gate "
            "for the prefill tier decision path"
        )
        assert out
    finally:
        await cell.stop()
    after = _counters()
    assert after["cell.handoffs"] == before["cell.handoffs"]
    assert after["cell.handoff_fallbacks"] == before["cell.handoff_fallbacks"]


def test_degenerate_specs_stay_colocated():
    # Prefill-only and decode-only cells cannot hand off.
    assert not _mock_cell(2, cell_disagg="2p0d")._disagg
    assert not _mock_cell(2, cell_disagg="0p2d")._disagg
    assert _mock_cell(2, cell_disagg="1p1d")._disagg


@pytest.mark.asyncio
async def test_short_and_sticky_prompts_route_to_decode_tier():
    """Short prompts and pinned sessions skip the prefill tier: their
    prefill is too small (or already owned) to be worth moving."""
    cell = _mock_cell(2, cell_disagg="1p1d")
    await cell.start()
    try:
        assert cell._disagg_decision((1, 2, 3), None, None) == "decode"
        cell.sessions["s-1"] = "r1"
        long_key = tuple(range(200))
        assert cell._disagg_decision(long_key, "s-1", None) == "decode"
        assert cell._disagg_decision(long_key, None, "gang-1") == "decode"
        assert cell._disagg_decision(long_key, None, None) == "handoff"
    finally:
        await cell.stop()


@pytest.mark.asyncio
async def test_prefix_hot_prompt_bypasses_prefill_tier():
    cell = _mock_cell(2, cell_disagg="1p1d")
    await cell.start()
    try:
        key = tuple(range(500, 700))
        # A decode-tier replica already holds most of this prefix.
        cell.router.table.note(key[:150], "r1")
        bypass0 = global_metrics.get("cell.tier.bypass")
        assert cell._disagg_decision(key, None, None) == "decode"
        assert global_metrics.get("cell.tier.bypass") == bypass0 + 1
        # A hit on the PREFILL replica doesn't count: the decode tier
        # would still have to prefill from scratch.
        key2 = tuple(range(900, 1100))
        cell.router.table.note(key2[:150], "r0")
        assert cell._disagg_decision(key2, None, None) == "handoff"
    finally:
        await cell.stop()


# --------------------------------------------------------------------- #
# Engine-level: byte-identical handoff (cpu llama-tiny)
# --------------------------------------------------------------------- #

GREEDY = dict(max_new_tokens=6, temperature=0.0)
# Long enough to clear disagg_min_key, short enough to clear the
# truncation gate (engine_max_seq 256 - 1 - max_new_tokens).
RAG_PROMPT = (
    "RAG context: "
    + "fleet telemetry shows sustained decode pressure on cell nine. " * 2
    + "question: summarize the incident."
)


def _engine_cfg(**kw):
    base = dict(
        model_name="llama-tiny", provider="cpu", dtype="float32",
        engine_slots=2, engine_max_seq=256, engine_chunk=8,
        engine_prefix_cache=1, engine_kvcache_host_mb=64,
    )
    base.update(kw)
    return LLMConfig(**base)


async def _reference_out(cfg, prompt=RAG_PROMPT):
    h = LLMHandler(cfg)
    await h.start()
    try:
        return await h.apredict(prompt, params=GenerationParams(**GREEDY))
    finally:
        await h.stop()


async def _disagg_out(cfg, prompt=RAG_PROMPT):
    cell = ServingCell(
        [LLMHandler(cfg) for _ in range(2)], cell_disagg="1p1d"
    )
    await cell.start()
    try:
        return await cell.apredict(prompt, params=GenerationParams(**GREEDY))
    finally:
        await cell.stop()


@pytest.mark.slow
@pytest.mark.parametrize(
    "paged,speculate,kv_int8,weight_quant",
    [
        (False, 0, True, None),
        (False, 4, False, "int4"),
        (True, 0, False, "int4"),
        (True, 4, True, None),
    ],
    ids=["dense-kvint8", "dense-spec-int4", "paged-int4", "paged-spec-kvint8"],
)
def test_handoff_byte_identity_matrix(paged, speculate, kv_int8, weight_quant):
    """The ISSUE 19 acceptance bar: greedy output across
    prefill→handoff→decode matches the colocated single-engine run byte
    for byte, across dense/paged × spec on/off × int8/int4 — and the
    decode replica really RESTORED the handed-off KV (a handoff was
    committed, nothing fell back, prefill work was saved)."""
    cfg = _engine_cfg(
        engine_paged_kv=paged,
        engine_page_size=16,
        engine_speculate=speculate,
        engine_kv_quantize="int8" if kv_int8 else None,
        engine_quant=weight_quant,
    )
    ref = asyncio.run(_reference_out(cfg))
    assert ref  # non-vacuous

    h0 = global_metrics.get("cell.handoffs")
    f0 = global_metrics.get("cell.handoff_fallbacks")
    saved0 = global_metrics.get("engine.kvcache.prefill_tokens_saved")
    out = asyncio.run(_disagg_out(cfg))

    assert out == ref
    assert global_metrics.get("cell.handoffs") - h0 >= 1
    assert global_metrics.get("cell.handoff_fallbacks") - f0 == 0
    assert global_metrics.get("engine.kvcache.prefill_tokens_saved") > saved0


@pytest.mark.slow
@pytest.mark.chaos
def test_handoff_corrupt_frame_falls_back_byte_identical():
    """A handoff frame corrupted on the wire is caught by the PR 14
    integrity framing: the import is rejected, the request falls back
    colocated, and greedy output is still byte-identical."""
    from pilottai_tpu.reliability.inject import global_injector

    cfg = _engine_cfg()
    ref = asyncio.run(_reference_out(cfg))

    h0 = global_metrics.get("cell.handoffs")
    f0 = global_metrics.get("cell.handoff_fallbacks")
    r0 = global_metrics.get("cell.handoff_rejected")
    i0 = global_metrics.get("engine.kvcache.integrity_failures")
    global_injector.arm("cell.handoff.corrupt", value=True, times=1)
    try:
        out = asyncio.run(_disagg_out(cfg))
    finally:
        global_injector.reset()

    assert out == ref
    assert global_metrics.get("cell.handoffs") - h0 == 1
    assert global_metrics.get("cell.handoff_fallbacks") - f0 == 1
    assert global_metrics.get("cell.handoff_rejected") - r0 >= 1
    assert global_metrics.get("engine.kvcache.integrity_failures") - i0 >= 1


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_prefill_replica_mid_handoff():
    """Chaos: the prefill replica dies under the prefill leg of a
    handoff. The request must fall back colocated (recovered_frac ==
    1.0) with byte-identical output; once health marks the replica
    unroutable the cell keeps serving without a prefill tier."""
    cfg = _engine_cfg()
    ref = asyncio.run(_reference_out(cfg))

    async def _run():
        cell = ServingCell(
            [LLMHandler(cfg) for _ in range(2)], cell_disagg="1p1d"
        )
        await cell.start()
        try:
            pre = next(
                r for r in cell.replicas.values() if r.tier == "prefill"
            )
            h0 = global_metrics.get("cell.handoffs")
            f0 = global_metrics.get("cell.handoff_fallbacks")

            # Kill: the prefill replica dies after the handoff is
            # committed — its KV export never comes back.
            def _dead(*a, **k):
                raise RuntimeError("replica killed mid-handoff")

            pre.handler.backend.export_request_kv = _dead
            out = await cell.apredict(RAG_PROMPT,
                                      params=GenerationParams(**GREEDY))
            assert out == ref
            assert global_metrics.get("cell.handoffs") - h0 == 1
            assert global_metrics.get("cell.handoff_fallbacks") - f0 == 1
            # Health catches up: the replica is out of the rotation and
            # the empty prefill tier degrades to colocated serving
            # without committing doomed handoffs.
            global_engine_health.mark_stalled(
                source=pre.health_source, reason="chaos kill",
                retry_after=60.0,
            )
            assert not pre.signals().routable()
            h1 = global_metrics.get("cell.handoffs")
            out2 = await cell.apredict(
                RAG_PROMPT + " and the follow-up question please.",
                params=GenerationParams(**GREEDY),
            )
            assert out2
            assert global_metrics.get("cell.handoffs") == h1
        finally:
            await cell.stop()
            global_engine_health.reset()

    asyncio.run(_run())
