"""Cross-host orchestrator↔agent control plane (SURVEY §2.14 / VERDICT
r2 next-step 6).

* in-process: a worker registers over real TCP, the router scores its
  RemoteAgent proxy like any local agent, tasks execute remotely and
  heartbeats feed load stats back;
* two-process: a REAL worker subprocess executes the orchestrator's task
  (the output proves which process ran it);
* the BASELINE config #5 story end-to-end: the task is routed to a
  remote agent, the worker host is SIGKILLed mid-execution, the failure
  flows into Serve's retry path, a healthy agent completes the task, and
  FaultTolerance flags the dead proxy on its stale heartbeat.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    ServeConfig,
)
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.core.task import Task
from pilottai_tpu.distributed import AgentWorker, RemoteAgent, ServeEndpoint
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
from pilottai_tpu.serve import Serve

REPO = Path(__file__).resolve().parents[1]


def _mock_agent(role="processor", specializations=(), latency=0.0):
    return BaseAgent(
        config=AgentConfig(
            role=role, specializations=list(specializations)
        ),
        llm=LLMHandler(
            LLMConfig(provider="mock"), backend=MockBackend(latency=latency)
        ),
    )


def _serve(agents=(), **cfg):
    return Serve(
        name="cp",
        agents=list(agents),
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(decomposition_enabled=False, **cfg),
    )


@pytest.mark.asyncio
async def test_remote_agent_executes_and_heartbeats():
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    worker = AgentWorker(
        "127.0.0.1", endpoint.port,
        [_mock_agent(specializations=["generic"])],
        heartbeat_interval=0.05,
    )
    await worker.start()
    try:
        deadline = time.time() + 10
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.05)
        assert serve.agents, "worker never registered"
        proxy = next(iter(serve.agents.values()))
        assert isinstance(proxy, RemoteAgent)

        task = await serve.add_task("analyze the quarterly data")
        result = await serve.wait_for(task.id, timeout=30)
        assert result.success
        assert task.agent_id == proxy.id  # it really went remote

        hb0 = proxy.heartbeat()
        await asyncio.sleep(0.2)
        assert proxy.heartbeat() > hb0, "heartbeats not flowing"
        assert proxy.status.is_available
        assert 0.0 <= proxy.queue_utilization <= 1.0
    finally:
        await worker.stop()
        await endpoint.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_heartbeat_carries_replica_routing_signals():
    """ISSUE 11 satellite: worker heartbeats ship the host's replica
    routing signals — per-class SLO burn/attainment, the engine's
    degrade rung and queue depth, and the health verdict — so a
    cell-style router ranks remote engines by the same policy as
    in-process replicas. Round-trip: seed the worker-side globals, wait
    one heartbeat, read the proxy's parsed signals."""
    from pilottai_tpu.distributed import ReplicaSignals
    from pilottai_tpu.obs import global_slo
    from pilottai_tpu.utils.metrics import global_metrics

    # Worker-side state the heartbeat must carry (the in-process test
    # shares globals with the endpoint — the signals still cross the
    # wire as JSON and come back parsed).
    for _ in range(5):
        global_slo.record("interactive", ok=False)
    global_metrics.set_gauge("engine.degrade_level", 2.0)
    global_metrics.set_gauge("engine.queue_depth", 7.0)
    # Earlier suites' batchers (chaos shed tests) leave their
    # max_queue_depth on the process-global gauge; clear it so the
    # soft-norm branch under test is the one that runs regardless of
    # file order (the 7/64 expectation below was order-dependent).
    global_metrics.set_gauge("engine.max_queue_depth", 0.0)

    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    worker = AgentWorker(
        "127.0.0.1", endpoint.port,
        [_mock_agent(specializations=["generic"])],
        heartbeat_interval=0.05,
    )
    await worker.start()
    try:
        deadline = time.time() + 10
        while not endpoint.worker_signals and time.time() < deadline:
            await asyncio.sleep(0.05)
        assert endpoint.worker_signals, "signals never arrived"
        signals = endpoint.worker_signals[worker.worker_id]
        assert signals["engine"]["degrade_level"] == 2.0
        assert signals["engine"]["queue_depth"] == 7.0
        # The router's shed thresholds read queue_frac — it must cross
        # the wire (7 deep / 64 soft norm without admission control).
        assert signals["engine"]["queue_frac"] == pytest.approx(7 / 64, abs=1e-3)
        assert signals["engine"]["healthy"] is True
        assert signals["slo"]["interactive"]["burn_rate"] > 0
        assert signals["slo"]["interactive"]["attainment"] < 1.0

        proxy = next(iter(serve.agents.values()))
        assert isinstance(proxy, RemoteAgent)
        assert proxy.signals == signals
        # The router-shape view parses into ReplicaSignals cleanly.
        parsed = ReplicaSignals.from_payload(proxy.routing_signals())
        assert parsed.replica_id == proxy.id
        assert parsed.degrade_level == 2
        assert parsed.queue_depth == 7
        assert parsed.queue_frac == pytest.approx(7 / 64, abs=1e-3)
        assert parsed.burn_rate["interactive"] > 0
        assert parsed.routable()
    finally:
        await worker.stop()
        await endpoint.stop()
        await serve.stop()
        global_slo.reset()
        global_metrics.set_gauge("engine.degrade_level", 0.0)
        global_metrics.set_gauge("engine.queue_depth", 0.0)


@pytest.mark.asyncio
async def test_worker_reconnects_after_connection_blip():
    """A dropped connection must not strand the worker (review finding:
    re-registration used to collide with the stale proxy's id and kill
    the handler): the worker re-dials, the dead proxy is replaced, and
    execution works again."""
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    worker = AgentWorker(
        "127.0.0.1", endpoint.port, [_mock_agent()],
        heartbeat_interval=0.05,
    )
    await worker.start()
    try:
        deadline = time.time() + 10
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.05)
        old = next(iter(serve.agents.values()))

        worker._writer.close()  # simulate a network blip
        deadline = time.time() + 20
        while time.time() < deadline:
            cur = serve.agents.get(old.id)
            if cur is not None and cur is not old and cur.status.is_available:
                break
            await asyncio.sleep(0.05)
        cur = serve.agents.get(old.id)
        assert cur is not None and cur is not old, "proxy never replaced"

        task = await serve.add_task("work after the blip")
        result = await serve.wait_for(task.id, timeout=30)
        assert result.success
        assert task.agent_id == cur.id
    finally:
        await worker.stop()
        await endpoint.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_endpoint_stop_with_live_worker_does_not_hang():
    """Review finding: wait_closed() on 3.12 blocks until every handler
    exits, so stop() must drop workers first — shutdown with a live
    worker attached is the normal production case."""
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    worker = AgentWorker(
        "127.0.0.1", endpoint.port, [_mock_agent()], reconnect=False,
    )
    await worker.start()
    try:
        deadline = time.time() + 10
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.05)
        assert serve.agents
        # Worker still connected: stop must complete promptly.
        await asyncio.wait_for(endpoint.stop(), timeout=10)
    finally:
        await worker.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_endpoint_rejects_bad_token():
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve, token="secret")
    await endpoint.start()
    worker = AgentWorker(
        "127.0.0.1", endpoint.port, [_mock_agent()],
        token="wrong", reconnect=False,
    )
    await worker.start()
    try:
        await asyncio.sleep(0.5)
        assert not serve.agents, "mis-tokened worker was registered"
    finally:
        await worker.stop()
        await endpoint.stop()
        await serve.stop()


_WORKER_CHILD = textwrap.dedent(
    """
    import asyncio, sys
    sys.path.insert(0, {repo!r})
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig
    from pilottai_tpu.distributed import AgentWorker
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend

    async def main():
        agent = BaseAgent(
            config=AgentConfig(
                role="remote-processor", specializations=["special"]
            ),
            llm=LLMHandler(
                LLMConfig(provider="mock"),
                backend=MockBackend(latency={latency}),
            ),
        )
        worker = AgentWorker(
            "127.0.0.1", {port}, [agent], heartbeat_interval=0.2,
        )
        await worker.start()
        print("WORKER-UP", flush=True)
        await worker.run_until_stopped()

    asyncio.run(main())
    """
)


def _spawn_script(tmp_path, script_text, timeout=60):
    """Spawn a worker subprocess, drain its output on a thread (a full
    pipe would block the child), and wait for its WORKER-UP line."""
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    proc = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    import queue as _q
    import threading

    lines: "_q.Queue[str]" = _q.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout],  # type: ignore[union-attr]
        daemon=True,
    ).start()
    seen = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            ln = lines.get(timeout=1.0)
            seen.append(ln)
            if "WORKER-UP" in ln:
                return proc
        except _q.Empty:
            if proc.poll() is not None:
                break
    proc.kill()
    raise AssertionError(
        "worker subprocess never came up; output:\n" + "".join(seen[-30:])
    )


def _spawn_worker(tmp_path, port, latency=0.0):
    return _spawn_script(
        tmp_path,
        _WORKER_CHILD.format(repo=str(REPO), port=port, latency=latency),
    )


@pytest.mark.asyncio
async def test_two_process_remote_execution(tmp_path):
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    proc = _spawn_worker(tmp_path, endpoint.port)
    try:
        deadline = time.time() + 30
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.1)
        assert serve.agents, "subprocess worker never registered"
        task = await serve.add_task("crunch these numbers remotely")
        result = await serve.wait_for(task.id, timeout=60)
        assert result.success
        proxy = next(iter(serve.agents.values()))
        assert task.agent_id == proxy.id
        assert proxy.role == "remote-processor"  # defined only in the child
    finally:
        proc.kill()
        await endpoint.stop()
        await serve.stop()


_NATIVE_WORKER_CHILD = textwrap.dedent(
    """
    import asyncio, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig, SamplingConfig
    from pilottai_tpu.distributed import AgentWorker
    from pilottai_tpu.engine.handler import LLMHandler

    async def main():
        # A REAL in-tree engine on this worker's own devices — the
        # deployment story: each TPU-VM host serves its agents locally.
        agent = BaseAgent(
            config=AgentConfig(role="native-worker"),
            llm=LLMHandler(LLMConfig(
                model_name="llama-tiny", provider="cpu", engine_slots=2,
                engine_max_seq=128, engine_chunk=4, dtype="float32",
                sampling=SamplingConfig(max_new_tokens=8, temperature=0.0),
            )),
        )
        worker = AgentWorker("127.0.0.1", {port}, [agent],
                             heartbeat_interval=0.2)
        await worker.start()
        print("WORKER-UP", flush=True)
        await worker.run_until_stopped()

    asyncio.run(main())
    """
)


@pytest.mark.asyncio
async def test_remote_agent_backed_by_native_engine(tmp_path):
    """The control plane's whole point: a worker host serving its agents
    with ITS OWN in-tree JAX engine. The orchestrator routes a task to
    it and gets a real generation back across the process boundary."""
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    proc = _spawn_script(
        tmp_path,
        _NATIVE_WORKER_CHILD.format(repo=str(REPO), port=endpoint.port),
        timeout=180,  # engine cold-start compiles before WORKER-UP
    )
    try:
        deadline = time.time() + 60
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.2)
        assert serve.agents, "native worker never registered"
        task = await serve.add_task("process this on the remote engine")
        # Engine cold-start (compile) happens inside the remote step.
        result = await serve.wait_for(task.id, timeout=240)
        assert result.success
        proxy = next(iter(serve.agents.values()))
        assert proxy.role == "native-worker"
        assert task.agent_id == proxy.id
    finally:
        proc.kill()
        await endpoint.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_sigkill_worker_reroutes_to_healthy_agent(tmp_path):
    """VERDICT r2 item 6's done-criterion: route to remote agent, SIGKILL
    its host mid-execution, the retry path re-routes, the task completes,
    and FaultTolerance flags the dead proxy."""
    local = _mock_agent(role="local-backup")
    serve = _serve(agents=[local], max_retry_attempts=3)
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    # Slow remote agent (30 s per step) specialized for the task type, so
    # the router prefers it over the local backup while it is alive.
    proc = _spawn_worker(tmp_path, endpoint.port, latency=30.0)
    ft = FaultTolerance(
        serve,
        config=FaultToleranceConfig(
            heartbeat_timeout=1.0, max_recovery_attempts=0,
        ),
    )
    try:
        deadline = time.time() + 30
        while len(serve.agents) < 2 and time.time() < deadline:
            await asyncio.sleep(0.1)
        remote = next(
            a for a in serve.agents.values() if isinstance(a, RemoteAgent)
        )
        task = await serve.add_task(Task(
            description="long remote job", type="special", timeout=120,
        ))
        # Wait until it is actually running on the remote agent.
        deadline = time.time() + 30
        while task.agent_id != remote.id and time.time() < deadline:
            await asyncio.sleep(0.05)
        assert task.agent_id == remote.id, "router did not pick the remote"
        await asyncio.sleep(0.3)

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        # Connection loss fails the in-flight future; Serve retries and
        # the local backup completes the SAME task.
        result = await serve.wait_for(task.id, timeout=60)
        assert result.success
        assert task.agent_id == local.id
        assert remote.status == AgentStatus.ERROR

        # FaultTolerance sees the stale heartbeat and flags/removes it.
        await asyncio.sleep(1.2)
        statuses = await ft.check_once()
        assert statuses[remote.id].name == "CRITICAL"
        assert remote.id not in serve.agents
    finally:
        if proc.poll() is None:
            proc.kill()
        await endpoint.stop()
        await serve.stop()


# --------------------------------------------------------------------- #
# Idempotent re-delivery + HMAC frames (VERDICT r3 next-step 8)
# --------------------------------------------------------------------- #

def test_frame_auth_sign_verify_tamper_replay():
    from pilottai_tpu.distributed.control_plane import FrameAuth

    a = FrameAuth("s3cret")
    b = FrameAuth("s3cret")
    signed = a.sign({"type": "execute", "x": 1})
    assert b.verify(dict(signed)) == {"type": "execute", "x": 1}
    # Replay of the same nonce is rejected.
    with pytest.raises(ConnectionError):
        b.verify(dict(signed))
    # Tampering breaks the MAC.
    evil = a.sign({"type": "execute", "x": 1})
    evil["x"] = 2
    with pytest.raises(ConnectionError):
        b.verify(evil)
    # Wrong key fails.
    with pytest.raises(ConnectionError):
        FrameAuth("other").verify(a.sign({"type": "hb"}))
    # Stale timestamp fails.
    stale = a.sign({"type": "hb"})
    stale["_ts"] = time.time() - 3600
    stale["_sig"] = a._mac({k: v for k, v in stale.items() if k != "_sig"})
    with pytest.raises(ConnectionError):
        b.verify(stale)


@pytest.mark.asyncio
async def test_hmac_gates_registration():
    """Matching secrets register and execute; a wrong secret never gets a
    proxy installed (frames fail verification at the endpoint)."""
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve, secret="hmac-key")
    await endpoint.start()
    good = AgentWorker(
        "127.0.0.1", endpoint.port, [_mock_agent()],
        heartbeat_interval=0.05, secret="hmac-key",
    )
    bad = AgentWorker(
        "127.0.0.1", endpoint.port, [_mock_agent(role="intruder")],
        heartbeat_interval=0.05, secret="wrong-key", reconnect=False,
    )
    await good.start()
    await bad.start()
    try:
        deadline = time.time() + 10
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.05)
        assert serve.agents, "good worker never registered"
        task = await serve.add_task("authenticated execution")
        result = await serve.wait_for(task.id, timeout=30)
        assert result.success
        await asyncio.sleep(0.3)
        assert all(
            getattr(a, "role", "") != "intruder"
            for a in serve.agents.values()
        ), "unauthenticated worker got a proxy installed"
    finally:
        await bad.stop()
        await good.stop()
        await endpoint.stop()
        await serve.stop()


@pytest.mark.asyncio
async def test_redelivered_task_executes_tools_exactly_once():
    """At-least-once delivery: the same task id delivered again (lost
    result / endpoint timeout / reroute back after reconnect) must NOT
    re-run side-effecting work — the worker serves the cached result."""
    serve = _serve()
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    agent = _mock_agent()
    calls = {"n": 0}
    real_execute = agent.execute_task

    async def counting_execute(task):
        calls["n"] += 1
        return await real_execute(task)

    agent.execute_task = counting_execute
    worker = AgentWorker(
        "127.0.0.1", endpoint.port, [agent], heartbeat_interval=0.05,
    )
    await worker.start()
    try:
        deadline = time.time() + 10
        while not serve.agents and time.time() < deadline:
            await asyncio.sleep(0.05)
        proxy = next(iter(serve.agents.values()))
        task = Task(description="side-effecting work", type="generic")

        r1 = await endpoint.execute(proxy, task)
        assert r1.success and calls["n"] == 1
        # Re-delivery of the SAME task id (simulates a retry after the
        # first result was lost in transit).
        r2 = await endpoint.execute(proxy, task)
        assert r2.success
        assert calls["n"] == 1, "re-delivered task re-executed the agent"
        assert r2.output == r1.output

        # A DIFFERENT task id still executes.
        other = Task(description="new work", type="generic")
        r3 = await endpoint.execute(proxy, other)
        assert r3.success and calls["n"] == 2
    finally:
        await worker.stop()
        await endpoint.stop()
        await serve.stop()
