"""KV integrity framing end to end (ISSUE 16 tentpole).

Unit half: the CRC/header primitives in engine/kvcache/integrity.py.
Engine half: a corrupted host-tier entry (spill-time and restore-time
chaos points) is DETECTED — counted under
``engine.kvcache.integrity_failures`` — and the session re-prefills to
byte-identical output instead of decoding silent wrong KV. Wire half:
a tampered migration frame rejects cleanly at import. Also home of the
injector thread-safety hammer the inject.py docstring points at.
"""

import json
import threading

import numpy as np
import pytest

from pilottai_tpu.distributed.cell import (
    corrupt_wire_payload,
    session_kv_from_wire,
    session_kv_to_wire,
)
from pilottai_tpu.engine.kvcache.index import KVCacheIndex
from pilottai_tpu.engine.kvcache.integrity import (
    KV_FRAME_VERSION,
    corrupt_arrays,
    entry_header,
    header_matches,
    kv_checksum,
)
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.metrics import global_metrics


@pytest.fixture(autouse=True)
def _clean_injector():
    global_injector.reset()
    yield
    global_injector.reset()


def _arrays(seed=0, n=48):
    rng = np.random.RandomState(seed)
    ks = rng.randn(2, 2, n, 4).astype(np.float32)
    vs = rng.randn(2, 2, n, 4).astype(np.float32)
    return ks, vs


# --------------------------------------------------------------------- #
# Unit: the framing primitives
# --------------------------------------------------------------------- #

def test_kv_checksum_detects_single_byte_flip():
    ks, vs = _arrays()
    crc = kv_checksum([ks, vs])
    assert crc == kv_checksum([ks.copy(), vs.copy()])  # content, not id
    corrupt_arrays([vs])
    assert kv_checksum([ks, vs]) != crc


def test_entry_header_round_trip_and_drift():
    ks, vs = _arrays()
    h = entry_header([ks, vs], kind="dense")
    assert h["v"] == KV_FRAME_VERSION and h["kind"] == "dense"
    assert header_matches(h, [ks, vs])
    # dtype doubles as the quant mode: an int8 panel against a float32
    # header is a quant-mode mismatch, not a reshape opportunity.
    assert not header_matches(h, [ks.astype(np.int8), vs])
    assert not header_matches(h, [ks[:, :, :24], vs])  # shape drift
    assert not header_matches(h, [ks])  # arity drift
    assert not header_matches({**h, "v": KV_FRAME_VERSION + 1}, [ks, vs])
    assert not header_matches(None, [ks, vs])


def test_corrupt_arrays_flips_first_nonempty_in_place():
    ks, vs = _arrays()
    empty = np.zeros((0,), np.float32)
    before = ks.copy()
    corrupt_arrays([empty, ks, vs])
    assert not np.array_equal(ks, before)  # skipped the empty one
    assert (ks.view(np.uint8).reshape(-1) != before.view(
        np.uint8).reshape(-1)).sum() == 1  # exactly one byte


def test_host_tier_entry_sealed_at_spill():
    idx = KVCacheIndex(host_bytes=1 << 20)
    ks, vs = _arrays(n=48)
    key = tuple(range(48))
    assert idx.host.put(key, (ks, vs), tokens=48, rows=48, kind="dense")
    e = idx.host.get(key)
    assert header_matches(e.header, e.copy.wait())
    assert e.copy.verify()
    # Rot the host-resident bytes: the sealed digest catches it.
    corrupt_arrays(list(e.copy.wait()))
    assert not e.copy.verify()


# --------------------------------------------------------------------- #
# Wire: tampered or mismatched migration frames reject cleanly
# --------------------------------------------------------------------- #

def _export_one(session="sess-i"):
    src = KVCacheIndex(host_bytes=1 << 20)
    ks, vs = _arrays(seed=3, n=70)
    key = tuple(range(70, 140))
    assert src.host.put(key, (ks, vs), tokens=70, rows=70, kind="dense")
    src.host.note_session(session, key + (7, 8))
    export = src.export_session(session)
    assert export is not None
    return export


def test_wire_tamper_rejected_at_import():
    export = _export_one()
    wire = json.loads(json.dumps(session_kv_to_wire(export)))
    assert corrupt_wire_payload(wire)
    fails = global_metrics.get("engine.kvcache.integrity_failures")
    dst = KVCacheIndex(host_bytes=1 << 20)
    got = dst.import_session(session_kv_from_wire(wire))
    assert got == {"accepted": 0, "tokens": 0, "rejected": 1}
    assert len(dst.host) == 0  # nothing restored from the rotten frame
    assert (
        global_metrics.get("engine.kvcache.integrity_failures") == fails + 1
    )


def test_wire_version_mismatch_raises():
    wire = session_kv_to_wire(_export_one())
    wire["v"] = KV_FRAME_VERSION + 1
    with pytest.raises(ValueError, match="version"):
        session_kv_from_wire(wire)


def test_import_rejects_header_drift():
    """A frame whose header disagrees with its arrays (quant-mode or
    layout skew between replicas) rejects before interpreting bytes."""
    export = _export_one()
    export["entries"][0]["header"]["dtype"] = ["int8", "int8"]
    dst = KVCacheIndex(host_bytes=1 << 20)
    got = dst.import_session(export)
    assert got["accepted"] == 0 and got["rejected"] == 1


# --------------------------------------------------------------------- #
# Engine: corruption detected, session re-prefills byte-identical
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize(
    "point", ["kvcache.spill.corrupt", "kvcache.restore.corrupt"],
    ids=["spill", "restore"],
)
def test_corrupted_host_entry_reprefills_byte_identical(point):
    """The PR 9 spill→evict→restore sequence with host RAM rot injected
    at the named point: the frame check catches it, the entry drops,
    ``integrity_failures`` counts it — and the resumed session falls
    back to re-prefill, so output matches the clean run byte for byte
    (slower, never wrong)."""
    from tests.test_multichip import _run_session_seq

    clean = _run_session_seq(None, paged=False)
    fails = global_metrics.get("engine.kvcache.integrity_failures")
    global_injector.arm(point, value=True, times=1)
    try:
        got = _run_session_seq(None, paged=False)
        fired = global_injector.fired(point)
    finally:
        global_injector.reset()
    assert got == clean
    assert fired == 1
    assert (
        global_metrics.get("engine.kvcache.integrity_failures") >= fails + 1
    )


# --------------------------------------------------------------------- #
# Injector thread-safety hammer (referenced by inject.py's docstring)
# --------------------------------------------------------------------- #

@pytest.mark.chaos
def test_injector_skip_and_times_exact_under_contention():
    """arm(times=1, skip=2): exactly one fire at the THIRD call site
    crossing, no matter how many threads race the counters."""
    global_injector.arm("test.hammer", value="hit", times=1, skip=2)
    hits, lock = [], threading.Lock()
    start = threading.Barrier(8)

    def worker():
        start.wait()
        for _ in range(10):
            got = global_injector.fire("test.hammer")
            if got is not None:
                with lock:
                    hits.append(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert hits == ["hit"]
    assert global_injector.fired("test.hammer") == 1
    assert not global_injector.armed("test.hammer")
