"""Overlapped admission (engine/batcher.py:_prep_loop) + host-gap obs.

The tentpole contract of the asynchronous device-feed pipeline:

* **Parity** — greedy output is byte-identical with
  ``engine_overlap_admission`` on vs off, across paged/dense caches ×
  speculate on/off, with the prefix cache enabled (so the prep thread's
  match path runs), a JSON-masked slot, and staggered budgets that
  finish slots mid-chunk. Moving admission prep to another thread must
  change WHEN work happens, never WHAT tokens come out.
* **Host-gap telemetry** — every decode dispatch observes
  ``engine.host_gap_ms`` and every fold's step-ring record carries the
  dispatch's gap, so BENCH sections (and regressions) are attributable.
* **Stress** (slow) — admissions, including chunked-prefill segments,
  arriving MID-decode while deadlines expire under overlap: per-slot
  early release + overlapped prep compose without hung futures, leaked
  slots or leaked pages.
"""

import time

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.obs import global_steps
from pilottai_tpu.reliability import DeadlineExceeded
from pilottai_tpu.utils.metrics import global_metrics

# Staggered budgets -> slots finish mid-chunk at different blocks; one
# slot decodes under the JSON grammar mask; two requests share a prompt
# prefix so the prefix-cache path participates.
REQS = (
    (list(range(3, 11)), 6, False),
    (list(range(3, 11)) + [17, 18], 12, False),   # shares an 8-token prefix
    (list(range(23, 36)), 9, True),
    (list(range(41, 48)), 2, False),
    (list(range(51, 60)), 15, False),
)


def _make_batcher(overlap, *, paged, speculate):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=96, cache_dtype=jnp.float32,
        chunk_size=6, paged=paged, page_size=16, speculate=speculate,
        prefix_cache=2, use_pallas=False, overlap_admission=overlap,
    )


def _run_batch(overlap, *, paged, speculate, reqs=REQS):
    b = _make_batcher(overlap, paged=paged, speculate=speculate)
    # Submit everything BEFORE starting so admission order (and with it
    # grouping/padding) is identical run to run.
    reqs_out = []
    for prompt, mnt, json_mode in reqs:
        req = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=mnt, json_mode=json_mode
        )
        b.submit(req)
        reqs_out.append(req)
    b.start()
    try:
        outs = [r.future.result(timeout=600) for r in reqs_out]
    finally:
        b.stop()
    return outs


@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 2), (True, 0), (True, 2)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
def test_overlap_matches_inline_greedy(paged, speculate):
    inline = _run_batch(False, paged=paged, speculate=speculate)
    overlapped = _run_batch(True, paged=paged, speculate=speculate)
    assert overlapped == inline, (
        f"overlapped admission changed greedy output (paged={paged}, "
        f"speculate={speculate})"
    )
    assert all(len(o) >= 1 for o in inline)  # non-vacuous


def test_host_gap_histogram_and_ring_fields():
    before = (
        global_metrics.snapshot()["histograms"]
        .get("engine.host_gap_ms", {})
        .get("count", 0)
    )
    _run_batch(True, paged=False, speculate=0)
    hist = global_metrics.snapshot()["histograms"].get("engine.host_gap_ms")
    assert hist is not None and hist["count"] > before, (
        "decode dispatches stopped observing engine.host_gap_ms"
    )
    assert hist["p50"] is not None
    chunks = [
        r for r in global_steps.snapshot() if r.get("kind") == "engine.chunk"
    ]
    assert chunks, "no engine.chunk records in the step ring"
    assert "host_gap_ms" in chunks[-1], (
        "per-dispatch host gap missing from the step ring record"
    )
    assert chunks[-1]["host_gap_ms"] >= 0.0


def test_engine_stays_serviceable_after_overlap_run():
    """The prep thread shuts down cleanly and a restarted batcher serves
    again — no slot/reservation leak survives a stop()."""
    b = _make_batcher(True, paged=True, speculate=0)
    req = GenRequest(prompt_ids=list(range(5, 15)), max_new_tokens=4)
    b.submit(req)
    b.start()
    assert len(req.future.result(timeout=300)) >= 1
    b.stop()
    assert not b._prep_reserved
    assert all(s is None for s in b._slots)


@pytest.mark.slow
def test_stress_admissions_mid_decode_with_deadlines_and_segments():
    """Admissions (short prompts AND a chunked-prefill long prompt)
    arrive while decode is in flight, some with deadlines that expire
    mid-decode. Pins that per-slot early release (PR 4) and overlapped
    admission compose: every future resolves (tokens or
    DeadlineExceeded), no slot stays occupied, no page leaks beyond the
    prefix index's deliberate pins, and the engine still serves after."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=128, cache_dtype=jnp.float32,
        chunk_size=4, paged=True, page_size=16, num_pages=24,
        prefill_chunk=32, prefix_cache=2, use_pallas=False,
        overlap_admission=True,
    )
    b.start()
    done, expired = 0, 0
    try:
        # Wave 1: keep the device decoding.
        wave1 = [
            GenRequest(prompt_ids=list(range(3 + i, 20 + i)),
                       max_new_tokens=24)
            for i in range(3)
        ]
        for r in wave1:
            b.submit(r)
        time.sleep(0.05)  # mid-decode
        # Wave 2: a long prompt that MUST segment (tail > 2 *
        # prefill_chunk = 64), plus short requests with tight deadlines.
        long_req = GenRequest(
            prompt_ids=list(range(2, 2 + 80)), max_new_tokens=8
        )
        b.submit(long_req)
        # i=0 is born practically expired (the prep thread's backlog
        # sweep must fail it without spending a prefill); the rest race
        # their decode budget.
        deadliners = [
            GenRequest(
                prompt_ids=list(range(60 + i, 75 + i)), max_new_tokens=64,
                deadline=time.monotonic() + (0.001 if i == 0 else 0.1 * i),
            )
            for i in range(4)
        ]
        for r in deadliners:
            b.submit(r)
        for r in wave1 + [long_req] + deadliners:
            try:
                out = r.future.result(timeout=600)
                assert isinstance(out, list)
                done += 1
            except DeadlineExceeded:
                expired += 1
        # Non-vacuous: the full-budget work completed AND at least the
        # born-expired request was failed with DeadlineExceeded.
        assert done >= 4
        assert expired >= 1
        assert len(long_req.future.result()) >= 1
        # Engine still serves after the churn.
        probe = GenRequest(prompt_ids=list(range(9, 21)), max_new_tokens=4)
        b.submit(probe)
        assert len(probe.future.result(timeout=300)) >= 1
        # No slot leaked...
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b._lock:
                if all(s is None for s in b._slots):
                    break
            time.sleep(0.05)
        with b._lock:
            assert all(s is None for s in b._slots)
        # ...and every page is either free or a deliberate prefix pin.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b._lock:
                total = b.num_pages - 1
                balanced = (
                    b.alloc.free_pages + b.page_index.pinned_pages == total
                )
            if balanced:
                break
            time.sleep(0.05)
        with b._lock:
            assert (
                b.alloc.free_pages + b.page_index.pinned_pages
                == b.num_pages - 1
            ), "pages leaked to dead slots"
    finally:
        b.stop()


def test_selection_failure_unwinds_committed_admissions():
    """A mid-selection exception (prefix match, eviction, the allocate
    assert) must roll back EVERYTHING the call already committed. The
    keep-alive catches in _prep_loop/_run only log: before the unwind,
    earlier members of the in-progress group kept their _prep_reserved
    entries and page allocations forever while their requests vanished
    from every queue — futures never resolved and the slot pool
    permanently shrank."""
    b = _make_batcher(True, paged=True, speculate=0)  # never started
    reqs = [
        GenRequest(prompt_ids=list(range(3, 11 + i)), max_new_tokens=4)
        for i in range(3)
    ]
    b._backlog.extend(reqs)
    free_before = b.alloc.free_pages
    calls = {"n": 0}
    orig = b._prefix_hit

    def flaky(req):
        calls["n"] += 1
        if calls["n"] == 3:  # two members already committed
            raise RuntimeError("injected prefix-index fault")
        return orig(req)

    b._prefix_hit = flaky
    with pytest.raises(RuntimeError):
        b._select_groups()
    assert not b._prep_reserved, "reservations leaked by failed selection"
    assert b.alloc.free_pages == free_before, "pages leaked"
    assert [r.prompt_ids for r in b._backlog] == [
        r.prompt_ids for r in reqs
    ], "backlog FIFO order not restored"
    # The engine recovers once the fault clears: selection now forms the
    # same admission group it would have originally.
    b._prefix_hit = orig
    groups, seg, _ = b._select_groups()
    assert seg is None
    assert [req for _, g in groups for _, req in g] == reqs


def test_all_expired_prep_skips_dispatch():
    """A _PreparedAdmission can wait in _prepped across a whole
    chunked-prefill segmentation — long past _select_groups' deadline
    sweep. If every member expired meanwhile, the fused prefill is 100%
    dead work: the device thread must fail the group (releasing pages
    and reservations) without spending the dispatch."""
    b = _make_batcher(True, paged=True, speculate=0)  # never started
    req = GenRequest(
        prompt_ids=list(range(3, 11)), max_new_tokens=4,
        deadline=time.monotonic() + 30,
    )
    b._backlog.append(req)
    free_before = b.alloc.free_pages
    groups, seg, epoch = b._select_groups()
    assert groups and seg is None
    prep = b._prepare_prefill(groups[0][1], groups[0][0], epoch=epoch)
    req.deadline = time.monotonic() - 0.001  # expired while queued

    def boom(_prep):
        raise AssertionError("dispatched a fully-expired group")

    b._dispatch_prefill = boom
    b._dispatch_admissions([prep])
    with pytest.raises(DeadlineExceeded):
        req.future.result(timeout=1)
    assert not b._prep_reserved, "reservation leaked on expired drop"
    assert b.alloc.free_pages == free_before, "pages leaked on expired drop"
