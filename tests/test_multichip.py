"""Tensor-parallel serving certification (ISSUE 13).

PR 13 makes the mesh first-class end to end: the paged KV pool and the
dense cache panels are CREATED sharded (kv-heads over ``model``, dense
slots over ``data`` — ``parallel/sharding.py:place_kv_cache``),
admission replicates over the ``data`` axis as balanced decode groups,
and per-dispatch collective time is attributed per mesh axis
(``parallel/collectives.py`` → ``engine.collective_frac[.axis]``).

Fast tests pin the pieces' arithmetic (gauge math from synthetic
dispatch records, the collective cost model, sharding-spec gating, the
data-group interleave, the HLO collective inspector). Slow tests run
the full engine on the virtual 8-device CPU mesh (tests/conftest.py)
and pin the acceptance bar: greedy output byte-identical sharded vs
single-device across dense/paged × spec on/off × int8 KV, the PR 9
spill→evict→restore path under sharding, and a PR 8 mid-decode
rebuild/recovery on a sharded engine — the multichip CI lane runs them
(tests.yml), same shape as the cell/chaos lanes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.parallel.collectives import (
    CollectiveModel,
    collective_bytes_by_axis,
    collective_ops,
)
from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.parallel.sharding import (
    kv_cache_shardings,
    kv_shard_axes,
    place_kv_cache,
    validate_serving_mesh,
)
from pilottai_tpu.utils.metrics import global_metrics

MESH = {"model": 2, "data": 2}


def _mesh(shape=None):
    return create_mesh(MeshConfig.from_dict(shape or MESH))


# --------------------------------------------------------------------- #
# Fast: collective gauge arithmetic from synthetic dispatch records
# (ISSUE 13 satellite — the gauge had never seen >1 device)
# --------------------------------------------------------------------- #

def test_collective_gauge_arithmetic_synthetic():
    """engine.collective_frac[.axis] from hand-fed dispatch records:
    frac = collective share of attributed device time, per-axis gauges
    split by the records' axis tags — pure window arithmetic, no
    engine."""
    from pilottai_tpu.obs.attribution import DeviceTimeAttributor
    from pilottai_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    attr = DeviceTimeAttributor(registry=reg, window_s=60.0)
    attr.configure(
        flops_per_token=1e9, platform="cpu", n_chips=8,
        mesh_axes=("data", "model"),
    )
    t0 = 1000.0
    attr.record("decode", 0.8, tokens=64, at=t0 + 1.0)
    attr.record("collective", 0.15, flops=0.0, axis="model", at=t0 + 1.0)
    attr.record("collective", 0.05, flops=0.0, axis="data", at=t0 + 1.0)
    snap = reg.snapshot()["gauges"]
    assert snap["engine.collective_frac"] == pytest.approx(0.2)
    assert snap["engine.collective_frac.model"] == pytest.approx(0.15)
    assert snap["engine.collective_frac.data"] == pytest.approx(0.05)
    # Cumulative counters: section consumers (bench) take deltas —
    # total and per-axis.
    counters = reg.snapshot()["counters"]
    assert counters["engine.attributed_collective_s"] == pytest.approx(0.2)
    assert counters["engine.attributed_collective_s.model"] == (
        pytest.approx(0.15)
    )
    assert counters["engine.attributed_collective_s.data"] == (
        pytest.approx(0.05)
    )
    # Off-window records prune back out.
    attr.record("decode", 0.1, tokens=8, at=t0 + 100.0)
    snap = reg.snapshot()["gauges"]
    assert snap["engine.collective_frac"] == pytest.approx(0.0)
    assert snap["engine.collective_frac.model"] == pytest.approx(0.0)
    # The batcher's fold path folds the per-axis split into ONE record
    # call (one lock/gauge pass on the reader thread); the window
    # arithmetic must match the separate-records form above.
    attr.record(
        "decode", 0.8, tokens=64, at=t0 + 101.0,
        collective={"model": 0.15, "data": 0.05},
    )
    snap = reg.snapshot()["gauges"]
    assert snap["engine.collective_frac"] == pytest.approx(0.2 / 1.1)
    assert snap["engine.collective_frac.model"] == pytest.approx(0.15 / 1.1)
    assert snap["engine.collective_frac.data"] == pytest.approx(0.05 / 1.1)
    counters = reg.snapshot()["counters"]
    assert counters["engine.attributed_collective_s"] == pytest.approx(0.4)
    assert counters["engine.attributed_collective_s.model"] == (
        pytest.approx(0.3)
    )


def test_collective_model_arithmetic():
    """The analytic per-dispatch estimate: model-axis bytes follow the
    2-all-reduces-per-layer + logits-gather formula, data-axis bytes
    exist only for the data-replicated paged pool's writes, and split()
    carves out of — never invents — measured wall time."""
    cfg = get_model_config("llama-tiny")
    mesh = _mesh({"model": 4, "data": 2})
    cm = CollectiveModel.for_mesh(
        mesh, cfg, platform="cpu", paged=True, kv_quantize=False,
    )
    assert cm is not None and cm.model_size == 4 and cm.data_size == 2
    # One block, 8 slots, 8 written tokens.
    est = cm.decode_seconds(1, 8, 8)
    assert est["model"] > 0 and est["data"] > 0
    # Closed form, model axis: rows = blocks * B / data; ring all-reduce
    # moves 2(M-1)/M of 2 activations per layer + (M-1)/M of the logits.
    rows = 1 * 8 / 2
    m = 4
    expect = (
        2.0 * cfg.n_layers * rows * cfg.hidden_size * cm.dtype_bytes
        * 2.0 * (m - 1) / m
        + rows * cfg.vocab_size * 4.0 * (m - 1) / m
    ) / cm.bytes_per_s
    assert est["model"] == pytest.approx(expect, rel=1e-6)
    # Data axis: written tokens' K/V rows all-gather across groups.
    expect_d = 8 * cm.kv_bytes_per_token * (2 - 1) / 2 / cm.bytes_per_s
    assert est["data"] == pytest.approx(expect_d, rel=1e-6)
    # split(): the estimate is capped at half the wall, compute +
    # collective always sum to the measured wall.
    compute, coll = cm.split(1.0, {"model": 0.9, "data": 0.3})
    assert compute + sum(coll.values()) == pytest.approx(1.0)
    assert sum(coll.values()) == pytest.approx(0.5)
    compute, coll = cm.split(1.0, {"model": 0.01})
    assert coll["model"] == pytest.approx(0.01)
    assert compute == pytest.approx(0.99)
    # Off-mesh: nothing to attribute.
    assert CollectiveModel.for_mesh(
        None, cfg, platform="cpu", paged=True, kv_quantize=False,
    ) is None
    single = create_mesh(MeshConfig(), jax.devices()[:1])
    assert CollectiveModel.for_mesh(
        single, cfg, platform="cpu", paged=True, kv_quantize=False,
    ) is None
    # Dense cache (batch sharded over data): no data-axis term.
    cm_dense = CollectiveModel.for_mesh(
        mesh, cfg, platform="cpu", paged=False, kv_quantize=False,
    )
    assert "data" not in cm_dense.decode_seconds(1, 8, 8)
    # fsdp as the batch axis: the pool-coherence term must land under
    # the mesh's REAL axis name — the per-axis gauges and declared
    # counters only exist for actual mesh axes.
    cm_fsdp = CollectiveModel.for_mesh(
        _mesh({"model": 2, "fsdp": 2}), cfg,
        platform="cpu", paged=True, kv_quantize=False,
    )
    assert cm_fsdp.data_axis == "fsdp" and cm_fsdp.data_size == 2
    est_f = cm_fsdp.decode_seconds(1, 8, 8)
    assert est_f["fsdp"] > 0 and "data" not in est_f


def test_collective_hlo_inspector():
    """collective_ops / collective_bytes_by_axis: parse op kind, payload
    bytes and replica groups out of HLO text and map groups to mesh
    axes — on a synthetic line (deterministic) AND on a real lowered
    sharded matmul (the premise check: GSPMD really inserts a
    model-axis all-reduce for a row-parallel contraction)."""
    mesh = _mesh({"model": 2, "data": 2})
    # Linear device ids grid is reshape(data=2, fsdp=1, model=2, seq=1):
    # model groups {0,1},{2,3}; data groups {0,2},{1,3}.
    text = (
        "  %ar = f32[4,128]{1,0} all-reduce(f32[4,128]{1,0} %x), "
        "replica_groups={{0,1},{2,3}}, to_apply=%add\n"
        "  %ag = bf16[8,64]{1,0} all-gather(bf16[8,32]{1,0} %y), "
        "replica_groups={{0,2},{1,3}}, dimensions={1}\n"
    )
    ops = collective_ops(text, mesh)
    assert [op.kind for op in ops] == ["all-reduce", "all-gather"]
    assert ops[0].axis == "model" and ops[0].bytes == 4 * 128 * 4
    assert ops[1].axis == "data" and ops[1].bytes == 8 * 64 * 2
    by_axis = collective_bytes_by_axis(text, mesh)
    assert by_axis == {"model": 4 * 128 * 4, "data": 8 * 64 * 2}

    # Async lowering splits each collective into a -start/-done pair,
    # BOTH carrying the full result payload (and -done without replica
    # groups); only the -start half may count or TPU-optimized HLO
    # reports ~2x bytes with half of it unattributable.
    async_text = (
        "  %s = f32[4,128]{1,0} all-reduce-start(f32[4,128]{1,0} %x), "
        "replica_groups={{0,1},{2,3}}, to_apply=%add\n"
        "  %d = f32[4,128]{1,0} all-reduce-done(f32[4,128]{1,0} %s)\n"
    )
    async_ops = collective_ops(async_text, mesh)
    assert len(async_ops) == 1 and async_ops[0].axis == "model"
    assert collective_bytes_by_axis(async_text, mesh) == {
        "model": 4 * 128 * 4
    }

    # The real thing: x @ w1 (col-parallel) @ w2 (row-parallel) must
    # all-reduce over the model axis.
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        np.ones((4, 16), np.float32), NamedSharding(mesh, P())
    )
    w1 = jax.device_put(
        np.ones((16, 32), np.float32), NamedSharding(mesh, P(None, "model"))
    )
    w2 = jax.device_put(
        np.ones((32, 16), np.float32), NamedSharding(mesh, P("model", None))
    )
    compiled = (
        jax.jit(lambda a, b, c: a @ b @ c).lower(x, w1, w2).compile()
    )
    hlo = compiled.as_text()
    real = collective_bytes_by_axis(hlo, mesh)
    assert real.get("model", 0) + real.get("other", 0) > 0, (
        "sharded row-parallel matmul lowered without any collective — "
        "the analytic model's premise does not hold"
    )


# --------------------------------------------------------------------- #
# Fast: KV sharding specs + placement
# --------------------------------------------------------------------- #

def test_kv_shard_axes_gating():
    mesh = _mesh({"model": 2, "data": 2})
    axes = kv_shard_axes(mesh, n_kv_heads=2, n_slots=4)
    assert axes["heads"] == "model"
    assert axes["slots"] == ("data",)
    assert axes["data_groups"] == 2
    # Non-divisible kv-heads: replicate heads, keep the data split.
    axes = kv_shard_axes(_mesh({"model": 4, "data": 2}), n_kv_heads=2,
                         n_slots=4)
    assert axes["heads"] is None and axes["data_groups"] == 2
    # Non-divisible slots: single admission group.
    axes = kv_shard_axes(mesh, n_kv_heads=2, n_slots=3)
    assert axes["slots"] is None and axes["data_groups"] == 1
    # Single device: nothing shards.
    single = create_mesh(MeshConfig(), jax.devices()[:1])
    axes = kv_shard_axes(single, n_kv_heads=2, n_slots=4)
    assert axes == {"heads": None, "slots": None, "data_groups": 1}


def test_place_kv_cache_layouts():
    """Dense panels shard (data, model); the paged pool shards kv-heads
    over model with pages replicated; lengths replicate everywhere."""
    from pilottai_tpu.ops.kvcache import KVCache
    from pilottai_tpu.ops.paged import PagedKVCache

    mesh = _mesh({"model": 2, "data": 2})
    dense = KVCache.create(2, 4, 64, 2, 8, dtype=jnp.float32,
                           quantized=True)
    dense = place_kv_cache(dense, mesh, n_kv_heads=2, n_slots=4)
    k0 = dense.layers[0][0]
    spec = k0.sharding.spec
    assert tuple(spec) == (("data",), "model", None, None) or tuple(
        spec
    ) == ("data", "model", None, None)
    assert dense.lengths.sharding.is_fully_replicated
    assert tuple(dense.scales[0][0].sharding.spec)[:2] == (
        tuple(spec)[0], "model",
    )

    pool = PagedKVCache.create(2, 4, 9, 16, 2, 8, dtype=jnp.float32)
    pool = place_kv_cache(pool, mesh, n_kv_heads=2, n_slots=4)
    pspec = tuple(pool.layers[0][0].sharding.spec)
    assert pspec[0] == "model" and all(s is None for s in pspec[1:])
    assert pool.lengths.sharding.is_fully_replicated

    # Nothing shardable → identity (no device_put, no spec tree).
    tiny = KVCache.create(1, 3, 16, 3, 4, dtype=jnp.float32)
    assert kv_cache_shardings(
        _mesh({"model": 2}), tiny, n_kv_heads=3, n_slots=3
    ) is None


def test_validate_serving_mesh_warnings():
    cfg = get_model_config("llama-tiny")  # 4 heads, 2 kv-heads
    report = validate_serving_mesh(_mesh({"model": 2, "data": 2}), cfg, 4)
    assert report["kv_heads_sharded"] and report["data_groups"] == 2
    assert report["warnings"] == []
    report = validate_serving_mesh(_mesh({"model": 4, "data": 2}), cfg, 3)
    assert not report["kv_heads_sharded"]
    assert report["data_groups"] == 1
    assert any("n_kv_heads" in w for w in report["warnings"])
    assert any("n_slots" in w for w in report["warnings"])


# --------------------------------------------------------------------- #
# Fast: data-axis admission groups
# --------------------------------------------------------------------- #

def test_free_slots_interleave_data_groups():
    """With data_groups=2, selection interleaves free slots across the
    contiguous group blocks, least-occupied group first — a burst
    admission spreads over every data shard's slots."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
        mesh=_mesh({"model": 2, "data": 2}),
    )
    try:
        assert b.data_groups == 2
        assert b._free_slot_indices() == [0, 2, 1, 3]
        b._slots[0] = object()  # occupy group 0
        assert b._free_slot_indices() == [2, 1, 3]
        b._slots[2] = object()  # both groups at 1 occupied
        assert b._free_slot_indices() == [1, 3]
    finally:
        b._slots = [None] * 4
        b.stop()


def test_pallas_gating_on_sharded_mesh():
    """Kernel/layout gates stay consistent on a mesh: the opt-in dense
    Pallas decode kernel (no shard_map wrapper) demotes to the XLA path
    when the dense panels would shard, and a paged Pallas engine whose
    slots don't divide the data axes keeps its pool REPLICATED (the
    unwrapped kernel must never see a model-sharded pool)."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    mesh = _mesh({"model": 2, "data": 2})
    # Dense + forced pallas on a shardable mesh → demoted to XLA.
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
        mesh=mesh, use_pallas=True,
    )
    try:
        assert not b.use_pallas
        assert not b.cache.layers[0][0].sharding.is_fully_replicated
    finally:
        b.stop()
    # Paged + forced pallas, slots don't divide data → sharded-kernel
    # gate fails; the pool must stay replicated (and kv_mesh unset).
    b = ContinuousBatcher(
        cfg, params, n_slots=3, max_seq_len=64, cache_dtype=jnp.float32,
        paged=True, page_size=16, mesh=mesh, use_pallas=True,
    )
    try:
        assert b.kv_mesh is None and b._kv_place_mesh is None
        assert b.cache.layers[0][0].sharding.is_fully_replicated
    finally:
        b.stop()
    # Paged + forced pallas, everything divides → sharded kernel AND
    # sharded pool.
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
        paged=True, page_size=16, mesh=mesh, use_pallas=True,
    )
    try:
        assert b.kv_mesh is mesh and b._kv_place_mesh is mesh
        assert not b.cache.layers[0][0].sharding.is_fully_replicated
    finally:
        b.stop()


def test_batcher_off_mesh_single_group():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
    )
    try:
        assert b.data_groups == 1 and b.mesh is None
        assert b.collective_model is None
        assert b._free_slot_indices() == [0, 1, 2, 3]
    finally:
        b.stop()


# --------------------------------------------------------------------- #
# Fast: the shard_map'd paged kernel itself (interpret mode) — the TPU
# serving path's per-shard dispatch, bit-identical to the plain kernel
# --------------------------------------------------------------------- #

def test_paged_kernel_sharded_matches_unsharded():
    """paged_decode_attention_sharded under shard_map (kv-heads over
    'model', slots over 'data') returns exactly the single-dispatch
    kernel's stats: heads are independent, so per-shard runs over
    disjoint head/slot blocks must reproduce the unsharded output bit
    for bit (the cross-shard merge lives in the o-projection, outside
    the kernel)."""
    from functools import partial

    from pilottai_tpu.ops.pallas.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_sharded,
        paged_sharding_ok,
    )

    mesh = _mesh({"model": 2, "data": 2})
    B, K, G, H, P_, n_pages, max_pages = 4, 2, 2, 8, 9, 16, 4
    assert paged_sharding_ok(mesh, B, K)
    assert not paged_sharding_ok(mesh, B, 3)  # heads don't divide
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, K * G, H), jnp.float32)
    k_pool = jax.random.normal(kk, (K, n_pages, P_, H), jnp.float32)
    v_pool = jax.random.normal(kv, (K, n_pages, P_, H), jnp.float32)
    table = jnp.asarray(
        np.arange(B * max_pages).reshape(B, max_pages) % (n_pages - 1),
        jnp.int32,
    )
    last_valid = jnp.asarray([30, 17, 0, 25], jnp.int32)
    kw = dict(n_blocks=2, scale=0.3, softcap=0.0, window=0, interpret=True)
    acc, m, l = paged_decode_attention(
        q, k_pool, v_pool, table, last_valid, **kw
    )
    acc_s, m_s, l_s = jax.jit(
        partial(paged_decode_attention_sharded, mesh, **kw)
    )(q, k_pool, v_pool, table, last_valid)
    np.testing.assert_array_equal(np.asarray(acc), np.asarray(acc_s))
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m_s))
    np.testing.assert_array_equal(np.asarray(l), np.asarray(l_s))


# --------------------------------------------------------------------- #
# Slow: the acceptance matrix — greedy byte-identity sharded vs single
# device across dense/paged × spec on/off × int8 KV (multichip CI lane)
# --------------------------------------------------------------------- #

PROMPTS = [
    "tensor parallel serving parity probe one",
    "the quick brown fox jumps over the lazy dog",
    "shard the kv pool over the model axis",
]


async def _generate_all(mesh_shape, *, paged, speculate, kv_int8,
                        max_new=8, weight_quant=None):
    import asyncio

    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    cfg = LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        mesh_shape=mesh_shape,
        engine_slots=4,
        engine_max_seq=128,
        engine_chunk=4,
        engine_speculate=speculate,
        engine_paged_kv=paged,
        engine_page_size=16,
        engine_kv_quantize="int8" if kv_int8 else None,
        engine_quant=weight_quant,
        dtype="float32",  # greedy argmax parity across shardings
    )
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        resps = await asyncio.gather(*[
            handler.generate_response(
                [ChatMessage(role="user", content=p)],
                params=GenerationParams(
                    max_new_tokens=max_new, temperature=0.0,
                ),
            )
            for p in PROMPTS
        ])
        return [r.content for r in resps]
    finally:
        await handler.stop()


@pytest.mark.slow
@pytest.mark.parametrize(
    "paged,speculate,kv_int8",
    [
        (False, 0, False), (False, 0, True),
        (False, 4, False), (False, 4, True),
        (True, 0, False), (True, 0, True),
        (True, 4, False), (True, 4, True),
    ],
    ids=[
        "dense", "dense-int8kv", "dense-spec", "dense-spec-int8kv",
        "paged", "paged-int8kv", "paged-spec", "paged-spec-int8kv",
    ],
)
@pytest.mark.asyncio
async def test_sharded_greedy_byte_identity(paged, speculate, kv_int8):
    """The ISSUE 13 acceptance bar: greedy output byte-identical on
    mesh={'model':2,'data':2} (sharded pool, balanced admission groups,
    per-shard dispatch) vs the single-device engine, for every
    cache/speculation/quantization combination the serving path has."""
    single = await _generate_all(
        {"data": 1}, paged=paged, speculate=speculate, kv_int8=kv_int8,
    )
    meshed = await _generate_all(
        MESH, paged=paged, speculate=speculate, kv_int8=kv_int8,
    )
    assert meshed == single
    assert any(s for s in single)  # non-vacuous


@pytest.mark.slow
@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
@pytest.mark.asyncio
async def test_sharded_int4_greedy_byte_identity(paged):
    """ISSUE 14: packed int4 weights compose with the sharded mesh path
    — Q4Tensor leaves shard like QTensor (q + group scales placed by
    the same logical axes) and greedy output on {'model':2,'data':2}
    stays byte-identical to the single-device int4 engine. Both boot
    paths quantize FROM the dense init, so the packed values match by
    construction (engine/native.py)."""
    single = await _generate_all(
        {"data": 1}, paged=paged, speculate=4, kv_int8=False,
        weight_quant="int4",
    )
    meshed = await _generate_all(
        MESH, paged=paged, speculate=4, kv_int8=False, weight_quant="int4",
    )
    assert meshed == single
    assert any(s for s in single)


# --------------------------------------------------------------------- #
# Slow: PR 9 kvcache tier under sharding — spill → evict → restore
# --------------------------------------------------------------------- #

def _kv_counters():
    return {
        k: global_metrics.get(f"engine.kvcache.{k}")
        for k in ("spills", "restores", "host_hits")
    }


def _run_session_seq(mesh, *, paged):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kwargs = dict(
        n_slots=2, max_seq_len=256, cache_dtype=jnp.float32, chunk_size=4,
        prefix_cache=1 if not paged else 4, kvcache_host_mb=64,
        use_pallas=False, mesh=mesh,
    )
    if paged:
        kwargs.update(paged=True, page_size=16)
    b = ContinuousBatcher(cfg, params, **kwargs)
    if paged and b.page_index is not None:
        b.page_index.capacity = 2
    base = [(i % 90) + 5 for i in range(80)]
    other = [(i % 70) + 11 for i in range(80)]
    resume = base + [7, 9, 11, 13]
    b.start()
    try:
        outs = []
        for prompt, sess in (
            (base, "s-mc"), (other, None), (resume, "s-mc"),
        ):
            req = GenRequest(
                prompt_ids=list(prompt), max_new_tokens=6, session_id=sess,
            )
            outs.append(b.submit(req).result(timeout=600))
        return outs
    finally:
        b.stop()


@pytest.mark.slow
@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_sharded_spill_evict_restore_parity(paged):
    """The PR 9 cold-tier path with a SHARDED pool: turn 1 caches,
    unrelated traffic evicts (the spill gathers from sharded panels),
    the session resume restores through the sharding-aware placer —
    outputs byte-identical to the single-device engine running the
    identical sequence, and the tier demonstrably exercised."""
    single = _run_session_seq(None, paged=paged)
    before = _kv_counters()
    meshed = _run_session_seq(_mesh(MESH), paged=paged)
    delta = {k: _kv_counters()[k] - before[k] for k in before}
    assert meshed == single
    assert delta["spills"] >= 1, "sharded run never spilled"
    assert delta["restores"] >= 1, "sharded run never restored"
    assert all(len(o) >= 1 for o in single)


# --------------------------------------------------------------------- #
# Slow: PR 8 fault domain under sharding — mid-decode rebuild/recovery
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.chaos
def test_sharded_mid_decode_rebuild_recovers_byte_identical():
    """An injected mid-decode dispatch failure on the SHARDED engine:
    the device-state rebuild re-places the pool on its mesh layout
    (place_kv_cache runs on the rebuild path), in-flight requests
    re-admit through recovery_max_attempts, and greedy output matches
    the unfaulted sharded run byte for byte."""
    from pilottai_tpu.reliability import global_injector

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    global_injector.reset()
    b = ContinuousBatcher(
        cfg, params, n_slots=2, max_seq_len=64, cache_dtype=jnp.float32,
        mesh=_mesh(MESH), recovery_max_attempts=2,
    )
    b.start()
    try:
        prompts = [[3, 4, 5], [6, 7]]
        ref = [
            b.submit(GenRequest(prompt_ids=list(p), max_new_tokens=12))
            .result(timeout=300)
            for p in prompts
        ]
        rebuilds = global_metrics.get("engine.rebuilds")
        global_injector.arm(
            "engine.step", RuntimeError("injected sharded fault"), times=1,
        )
        futs = [
            b.submit(GenRequest(prompt_ids=list(p), max_new_tokens=12))
            for p in prompts
        ]
        got = [f.result(timeout=300) for f in futs]
        assert got == ref
        assert global_injector.fired("engine.step") == 1
        assert global_metrics.get("engine.rebuilds") == rebuilds + 1
        # The rebuilt pool kept its mesh layout.
        k0 = b.cache.layers[0][0]
        assert not k0.sharding.is_fully_replicated
    finally:
        global_injector.reset()
        b.stop()


# --------------------------------------------------------------------- #
# Slow: the wired gauge reports nonzero under a sharded soak
# --------------------------------------------------------------------- #

@pytest.mark.slow
def test_collective_frac_nonzero_under_sharded_soak():
    """ISSUE 13 satellite end-to-end: a real sharded decode soak drives
    engine.collective_frac and .model above zero (the gauge existed
    since PR 6 and had never reported a nonzero value), while the
    single-device contract — exactly 0 — still holds."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(
        cfg, params, n_slots=4, max_seq_len=128, cache_dtype=jnp.float32,
        paged=True, page_size=16, mesh=_mesh(MESH),
    )
    assert b.collective_model is not None
    b.start()
    try:
        futs = [
            b.submit(GenRequest(
                prompt_ids=[5 + i, 6, 7, 8], max_new_tokens=16,
            ))
            for i in range(4)
        ]
        for f in futs:
            f.result(timeout=300)
    finally:
        b.stop()
    assert global_metrics.get("engine.collective_frac") > 0.0
    assert global_metrics.get("engine.collective_frac.model") > 0.0
    assert (
        global_metrics.get("engine.attributed_collective_s") > 0.0
    )
