"""Trainer: sharded train step on the virtual 8-device mesh.

Strategy per SURVEY.md §4: CPU-jax + forced multi-device host platform;
assert the control decision (loss finite & decreasing, shardings stable,
remat equivalence) rather than model quality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_train
from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.train import Trainer, TrainConfig, next_token_loss, synthetic_batches


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(MeshConfig(data=1, fsdp=2, model=2, seq=2))


def test_train_step_runs_and_improves(mesh):
    cfg = get_model_config("llama-tiny")
    t = Trainer(
        cfg,
        TrainConfig(learning_rate=1e-2, warmup_steps=1, total_steps=50),
        mesh=mesh,
    )
    state = t.init(jax.random.key(0))
    # One fixed batch, repeated: loss must drop (memorization).
    batch = next(synthetic_batches(cfg, 4, 32))
    losses = []
    for _ in range(8):
        state, m = t.step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_train_step_state_shardings_stable(mesh):
    cfg = get_model_config("llama-tiny")
    t = Trainer(cfg, TrainConfig(warmup_steps=1, total_steps=10), mesh=mesh)
    state = t.init(jax.random.key(0))
    it = synthetic_batches(cfg, 4, 32)
    state, _ = t.step(state, next(it))
    sh1 = jax.tree.map(lambda a: a.sharding, state[0])
    state, _ = t.step(state, next(it))
    sh2 = jax.tree.map(lambda a: a.sharding, state[0])
    assert jax.tree.all(jax.tree.map(lambda a, b: a == b, sh1, sh2))


def test_params_actually_sharded(mesh):
    cfg = get_model_config("llama-tiny")
    t = Trainer(cfg, TrainConfig(), mesh=mesh)
    params, _ = t.init(jax.random.key(0))
    wq = params["layers"]["attn"]["wq"]
    # TP: q-dim axis split over 'model' (2 shards).
    assert wq.sharding.spec[-1] == "model"
    assert len(wq.addressable_shards) == 8


def test_remat_matches_no_remat():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(16)[None, :], (2, 16))
    valid = jnp.asarray([16, 12], jnp.int32)

    def loss(p, remat):
        logits, _moe_aux = forward_train(
            p, cfg, tokens, positions, valid, remat=remat
        )
        return next_token_loss(logits, tokens, valid)

    g1 = jax.grad(lambda p: loss(p, True))(params)
    g2 = jax.grad(lambda p: loss(p, False))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5), g1, g2
    )


def test_gemma_family_trains(mesh):
    cfg = get_model_config("gemma-tiny")
    t = Trainer(cfg, TrainConfig(warmup_steps=1, total_steps=10), mesh=mesh)
    state = t.init(jax.random.key(1))
    state, m = t.step(state, next(synthetic_batches(cfg, 4, 24, seed=3)))
    assert np.isfinite(float(m["loss"]))


def test_loss_ignores_padding():
    cfg = get_model_config("llama-tiny")
    B, T, V = 2, 8, cfg.vocab_size
    logits = jnp.zeros((B, T, V), jnp.float32)
    tokens = jnp.zeros((B, T), jnp.int32)
    full = next_token_loss(logits, tokens, jnp.asarray([8, 8], jnp.int32))
    half = next_token_loss(logits, tokens, jnp.asarray([4, 4], jnp.int32))
    # Uniform logits → identical mean loss regardless of mask size.
    np.testing.assert_allclose(float(full), float(half), rtol=1e-6)
    np.testing.assert_allclose(float(full), float(np.log(V)), rtol=1e-5)
