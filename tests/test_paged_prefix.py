"""Composed fast paths on the paged KV cache (VERDICT r3 next-step 1).

Round 3's speculation and prefix caching were dense-only; the paged
cache — the long-context path, and the auto-selected one for large
contexts — silently lost both. These tests certify the composition:
block-granular prefix caching (``engine/page_prefix.py``) and
speculative decoding (``decode_chunk_spec`` with a block table) each
produce BIT-IDENTICAL greedy output to a cold dense engine, separately
and together, on one device and on the virtual 8-device mesh.
"""

import asyncio

import numpy as np
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.page_prefix import PagePrefixIndex
from pilottai_tpu.engine.types import ChatMessage, GenerationParams
from pilottai_tpu.ops.paged import PageAllocator
from pilottai_tpu.utils.metrics import global_metrics


# --------------------------------------------------------------------- #
# PagePrefixIndex + refcounted allocator units
# --------------------------------------------------------------------- #

def test_index_match_is_proper_prefix_and_block_granular():
    alloc = PageAllocator(num_pages=17, page_size=4, n_slots=4,
                          max_pages_per_slot=8)
    idx = PagePrefixIndex(page_size=4, capacity_pages=8)
    ids = list(range(100, 116))  # 4 full blocks
    assert alloc.allocate(0, len(ids) + 4)
    pages = [int(p) for p in alloc.table[0, :4]]
    idx.register(ids, pages, alloc)

    # Exact ids: only 3 blocks may match (a tail token must remain).
    node = idx.match(ids)
    assert node is not None and node.depth == 3
    assert list(node.path_pages) == pages[:3]
    # Longer prompt sharing all blocks: full 4-block chain.
    assert idx.match(ids + [7, 8]).depth == 4
    # Diverging within block 2: only 1 block shared.
    div = ids[:6] + [999] * 10
    assert idx.match(div).depth == 1
    # Diverging in block 0: no match.
    assert idx.match([999] * 16) is None


def test_allocator_refcounts_shared_pages():
    alloc = PageAllocator(num_pages=9, page_size=4, n_slots=4,
                          max_pages_per_slot=8)
    assert alloc.allocate(0, 8)          # 2 private pages
    shared = list(alloc._held[0])
    # Pin both (the index), then release the slot: pages stay live.
    for p in shared:
        alloc.pin(p)
    alloc.release(0)
    assert alloc.free_pages == 8 - 2
    # Map them into a new slot as a shared prefix + 1 fresh page.
    assert alloc.allocate(1, 12, prefix_pages=shared)
    assert list(alloc.table[1, :2]) == shared
    alloc.release(1)
    assert alloc.free_pages == 8 - 2     # still pinned
    for p in shared:
        alloc.unpin(p)
    assert alloc.free_pages == 8         # everything back


def test_index_eviction_respects_protect_and_leaves():
    alloc = PageAllocator(num_pages=17, page_size=2, n_slots=4,
                          max_pages_per_slot=8)
    idx = PagePrefixIndex(page_size=2, capacity_pages=16)
    assert alloc.allocate(0, 8)
    pages = [int(p) for p in alloc.table[0, :4]]
    idx.register(list(range(8)), pages, alloc)
    alloc.release(0)
    free0 = alloc.free_pages
    # Protected chain: nothing evictable.
    assert idx.evict(4, alloc, protect=frozenset(pages)) == 0
    # Unprotected: leaves evict deepest-first (leaf-only), pages free.
    assert idx.evict(2, alloc) == 2
    assert alloc.free_pages == free0 + 2
    assert idx.match(list(range(8)) + [1]).depth == 2


def test_index_capacity_bounds_pins():
    alloc = PageAllocator(num_pages=33, page_size=2, n_slots=4,
                          max_pages_per_slot=16)
    idx = PagePrefixIndex(page_size=2, capacity_pages=3)
    assert alloc.allocate(0, 16)
    pages = [int(p) for p in alloc.table[0, :8]]
    idx.register(list(range(16)), pages, alloc)
    assert idx.pinned_pages <= 3
    alloc.release(0)


# --------------------------------------------------------------------- #
# Engine parity: every fast-path combination vs a cold dense engine
# --------------------------------------------------------------------- #

LONG = ("You are the orchestrator. Analyze the task and respond with "
        "strict JSON as instructed by the rules preamble. Task: ")


async def _run_engine(prompts, *, paged=False, speculate=0, prefix=0,
                      mesh=None, max_new=14):
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=4,
        engine_max_seq=256, engine_chunk=4, dtype="float32",
        engine_paged_kv=paged, engine_page_size=16,
        engine_speculate=speculate, engine_prefix_cache=prefix,
        mesh_shape=mesh,
    ))
    await h.start()
    try:
        outs = []
        for p in prompts:
            r = await h.generate_response(
                [ChatMessage(content=p)],
                params=GenerationParams(max_new_tokens=max_new,
                                        temperature=0.0),
            )
            outs.append(r.content)
        return outs, h.get_metrics()["backend"]
    finally:
        await h.stop()


@pytest.mark.asyncio
@pytest.mark.parametrize("speculate", [0, 4])
async def test_paged_prefix_hit_identical_to_cold_dense(speculate):
    """Exact repeat on the paged engine must hit the block-granular
    cache (prompt >= one 16-token page) and emit the same bits as a
    cold DENSE engine — with and without speculation on top."""
    prompt = LONG + "summarize the quarterly report"
    (want,), _ = await _run_engine([prompt])

    h0 = global_metrics.get("engine.prefix_hits")
    outs, metrics = await _run_engine(
        [prompt, prompt, prompt],
        paged=True, speculate=speculate, prefix=8,
    )
    assert outs == [want] * 3
    assert global_metrics.get("engine.prefix_hits") - h0 >= 1
    assert metrics.get("prefix_pages", 0) >= 1


@pytest.mark.asyncio
async def test_paged_spec_identical_to_plain_dense():
    """decode_chunk_spec over the block table: greedy output parity on
    repetitive AND novel prompts (prefix cache off isolates spec)."""
    prompts = [LONG + "abc abc abc abc", "one shot novel text"]
    want, _ = await _run_engine(prompts)
    got, _ = await _run_engine(prompts, paged=True, speculate=4)
    assert got == want


@pytest.mark.asyncio
async def test_paged_block_sharing_without_full_repeat():
    """Block granularity replaces the dense store's LCP derivation: two
    different prompts sharing the page-aligned preamble make the THIRD
    distinct prompt hit — no full repeat ever seen."""
    (want3,), _ = await _run_engine([LONG + "third unseen task"])
    h0 = global_metrics.get("engine.prefix_hits")
    outs, _ = await _run_engine(
        [LONG + "first task", LONG + "second very different task",
         LONG + "third unseen task"],
        paged=True, prefix=8,
    )
    hits = global_metrics.get("engine.prefix_hits") - h0
    assert hits >= 1, "shared page-aligned preamble never hit"
    assert outs[2] == want3


@pytest.mark.asyncio
async def test_paged_all_features_on_mesh():
    """The full composition on the virtual 8-device mesh: paged KV +
    speculation + block-granular prefix cache + model/data sharding,
    parity against the same engine's own miss output."""
    prompt = LONG + "mesh parity with every fast path on"
    (want,), _ = await _run_engine([prompt])
    outs, _ = await _run_engine(
        [prompt, prompt],
        paged=True, speculate=4, prefix=8,
        mesh={"model": 2, "data": 2},
    )
    assert outs == [want, want]


@pytest.mark.asyncio
async def test_paged_prefix_pressure_evicts_not_starves():
    """A pool too small to hold cached chains + a new admission must
    reclaim cached pages instead of deadlocking the queue."""
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=2,
        engine_max_seq=512, engine_chunk=4, dtype="float32",
        engine_paged_kv=True, engine_page_size=16, engine_kv_pages=13,
        engine_prefix_cache=8,
    ))
    await h.start()
    try:
        outs = []
        for i in range(5):
            outs.append(await h.apredict(
                f"task number {i}: " + "pad " * 30,
                params=GenerationParams(max_new_tokens=8, temperature=0.0),
            ))
        assert all(isinstance(o, str) for o in outs)
    finally:
        await h.stop()
