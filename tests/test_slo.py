"""SLO observability layer tests: per-class attainment/burn-rate
arithmetic, flight-class separation, Prometheus exposition of the new
series, the export-completeness wiring check, and the HTTP edge's
slo_class threading."""

import asyncio
import json

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.obs import (
    export_completeness,
    global_flight,
    global_slo,
    metrics_snapshot,
    prometheus_text,
)
from pilottai_tpu.obs.slo import DEFAULT_CLASSES, SLOClass, SLOTracker
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


def _mock_handler(**mock_kwargs) -> LLMHandler:
    return LLMHandler(
        LLMConfig(provider="mock", model_name="mock-slo"),
        backend=MockBackend(**mock_kwargs),
    )


# ---------------------------------------------------------------------- #
# Tracker arithmetic
# ---------------------------------------------------------------------- #


def test_burn_rate_arithmetic_on_synthetic_miss_pattern():
    """Burn rate = miss rate over the burn window ÷ budgeted miss rate.
    A 99% objective budgets 1% misses: 10 misses in 100 requests burns
    at 10x; zero misses burns at 0."""
    registry = MetricsRegistry()
    tracker = SLOTracker(
        classes=[SLOClass(name="interactive", ttft_s=1.0,
                          attainment_target=0.99)],
        registry=registry,
    )
    for i in range(100):
        # Every 10th request misses its TTFT target.
        tracker.record(
            "interactive", ttft_s=5.0 if i % 10 == 0 else 0.1, ok=True
        )
    g = registry.snapshot()["gauges"]
    assert g["slo.interactive.attainment"] == pytest.approx(0.90)
    assert g["slo.interactive.burn_rate"] == pytest.approx(10.0)
    assert registry.get("slo.interactive.requests") == 100
    assert registry.get("slo.interactive.missed") == 10

    # Failures are misses regardless of timing — a shed request consumed
    # budget even though no latency was observed.
    tracker.record("interactive", ok=False)
    assert registry.get("slo.interactive.missed") == 11

    # An all-met stream converges attainment back up and burn reflects
    # the window's miss fraction, not all-time counters.
    tracker2 = SLOTracker(
        classes=[SLOClass(name="batch", ttft_s=10.0,
                          attainment_target=0.95)],
        registry=MetricsRegistry(), window=50,
    )
    for _ in range(50):
        tracker2.record("batch", ttft_s=0.5)
    assert tracker2.snapshot()["batch"]["attainment"] == 1.0
    assert tracker2.snapshot()["batch"]["burn_rate"] == 0.0


def test_burn_window_outlives_the_attainment_count_window():
    """Review regression: a fixed maxlen=window ledger silently shrank
    the 300 s burn window to ~window/rate seconds at high request rates.
    Misses older than the last `window` entries but inside the burn
    window must still burn budget (and attainment stays count-bounded)."""
    import time as _time

    registry = MetricsRegistry()
    tracker = SLOTracker(
        classes=[SLOClass(name="interactive", ttft_s=1.0,
                          attainment_target=0.99)],
        registry=registry, window=100, burn_window_s=300.0,
    )
    t0 = _time.monotonic()
    # 100 misses, then 100 hits, all within 20 s of "now": the count
    # window (last 100) is all hits, the burn window sees all 200.
    for i in range(100):
        tracker.record("interactive", ttft_s=5.0, at=t0 + i * 0.05)
    for i in range(100):
        tracker.record("interactive", ttft_s=0.1, at=t0 + 5.0 + i * 0.05)
    g = registry.snapshot()["gauges"]
    assert g["slo.interactive.attainment"] == pytest.approx(1.0)
    assert g["slo.interactive.burn_rate"] == pytest.approx(50.0)  # 0.5/0.01


def test_burn_rate_decays_after_traffic_stops():
    """Review regression: the gauges are only written when a flight
    finishes, so a scaler reading them raw after an outage-then-silence
    would see the final burn value forever. refresh_gauges recomputes
    against NOW; the autoscaler calls it before every read."""
    import time as _time

    registry = MetricsRegistry()
    tracker = SLOTracker(registry=registry, burn_window_s=300.0)
    old = _time.monotonic() - 400.0  # outside the burn window by now
    for _ in range(10):
        tracker.record("interactive", ok=False, at=old)
    # Frozen at record time: every request in the then-current window
    # missed, so the gauge reads full burn.
    assert registry.snapshot()["gauges"]["slo.interactive.burn_rate"] > 1.0
    tracker.refresh_gauges()
    g = registry.snapshot()["gauges"]
    assert g["slo.interactive.burn_rate"] == 0.0
    # Attainment is count-windowed (those misses are still the last
    # 1024 flights) — only the TIME-based burn signal decays.
    assert g["slo.interactive.attainment"] == 0.0


def test_unconstrained_and_unobserved_dimensions_do_not_miss():
    """None targets and unobserved dimensions never fail a request — a
    1-token reply has no TPOT; a class without an e2e target ignores
    e2e entirely."""
    cls = SLOClass(name="x", ttft_s=1.0, tpot_s=None, e2e_s=None)
    assert cls.met(ttft_s=0.5, tpot_s=99.0, e2e_s=99.0)
    assert cls.met(ttft_s=None, tpot_s=None, e2e_s=None)
    assert not cls.met(ttft_s=2.0, tpot_s=None, e2e_s=None)


def test_unknown_class_falls_back_to_default():
    registry = MetricsRegistry()
    tracker = SLOTracker(registry=registry)
    tracker.record("no-such-class", ttft_s=0.1)
    assert registry.get("slo.interactive.requests") == 1


def test_slo_reset_clears_windows_but_not_counters():
    registry = MetricsRegistry()
    tracker = SLOTracker(registry=registry)
    tracker.record("interactive", ttft_s=99.0)  # miss
    assert registry.snapshot()["gauges"]["slo.interactive.attainment"] == 0.0
    tracker.reset()
    snap = tracker.snapshot()["interactive"]
    assert snap["window"] == 0
    assert snap["attainment"] == 1.0
    assert snap["burn_rate"] == 0.0
    # Cumulative counters survive — bench sections measure by delta.
    assert registry.get("slo.interactive.requests") == 1


# ---------------------------------------------------------------------- #
# Flight integration: per-class separation
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_per_class_flight_separation_when_interleaved():
    """Interactive and batch requests interleaving through one handler
    must land in their OWN class ledgers (counters and per-class
    histograms), not blend."""
    handler = _mock_handler(latency=0.002)
    global_metrics.reset_histograms("slo.")
    base = (
        global_metrics.get("slo.interactive.requests"),
        global_metrics.get("slo.batch.requests"),
    )

    async def one(i):
        params = GenerationParams(
            slo_class="interactive" if i % 2 == 0 else "batch",
            max_new_tokens=8,
        )
        await handler.generate_response([f"ping {i}"], params=params)

    await asyncio.gather(*[one(i) for i in range(8)])
    assert (
        global_metrics.get("slo.interactive.requests") - base[0] == 4
    )
    assert global_metrics.get("slo.batch.requests") - base[1] == 4
    hists = global_metrics.snapshot()["histograms"]
    assert hists["slo.interactive.ttft_s"]["count"] >= 4
    assert hists["slo.batch.ttft_s"]["count"] >= 4


@pytest.mark.asyncio
async def test_slo_class_defaults_when_absent():
    """A request with no class lands in the default class — no traffic
    is exempt from SLO accounting."""
    handler = _mock_handler(latency=0.001)
    base = global_metrics.get("slo.interactive.requests")
    await handler.apredict("untagged request")
    assert global_metrics.get("slo.interactive.requests") == base + 1


@pytest.mark.asyncio
async def test_orchestrator_task_priority_maps_to_slo_class():
    """Agent LLM steps carry the task-kind class: LOW-priority tasks run
    as batch, NORMAL as interactive."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig
    from pilottai_tpu.core.task import Task

    agent = BaseAgent(
        config=AgentConfig(role="worker"), llm=_mock_handler()
    )
    assert agent._slo_class_for(Task(description="x", priority="low")) == (
        "batch"
    )
    assert agent._slo_class_for(Task(description="x")) == "interactive"
    assert agent._slo_class_for(None) == "interactive"

    await agent.start()
    base = global_metrics.get("slo.batch.requests")
    await agent.execute_task(Task(description="background sweep",
                                  priority="low"))
    await agent.stop()
    # Every LLM step of the LOW-priority task (analysis, planning,
    # evaluation) recorded as batch.
    assert global_metrics.get("slo.batch.requests") >= base + 2


# ---------------------------------------------------------------------- #
# Exposition: Prometheus + export completeness
# ---------------------------------------------------------------------- #


def test_prometheus_exposition_carries_slo_and_attribution_series():
    """slo.* / engine.mfu / engine.collective_frac surface in the text
    exposition as parseable sample lines (declared series appear even
    before first observation)."""
    registry = MetricsRegistry()
    SLOTracker(registry=registry)
    from pilottai_tpu.obs.attribution import DeviceTimeAttributor

    attr = DeviceTimeAttributor(registry=registry)
    attr.configure(flops_per_token=1e9, platform="cpu",
                   mesh_axes=("model", "data"))
    attr.record("decode", 0.01, tokens=4)
    text = prometheus_text(metrics_snapshot(registry=registry))
    for needle in (
        "pilottai_slo_interactive_attainment",
        "pilottai_slo_interactive_burn_rate",
        "pilottai_slo_batch_attainment",
        "pilottai_slo_interactive_ttft_s_count",
        "pilottai_engine_mfu",
        "pilottai_engine_collective_frac",
        "pilottai_engine_collective_frac_model",
        "pilottai_engine_device_busy_frac",
    ):
        assert needle in text, needle
    # Parseability: every non-comment line is "name{labels} value".
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        assert len(parts) == 2 and parts[0], line
        float(parts[1])  # must parse


def test_export_completeness_walks_declared_series():
    """The CI wiring check: every registry-declared series must reach
    both metrics_snapshot and the Prometheus exposition; a series that
    an exporter drops (simulated here with a name the sanitizer
    collides) is reported."""
    registry = MetricsRegistry()
    registry.declare("engine.mfu", "gauge")
    registry.declare("slo.interactive.requests", "counter")
    registry.declare("request.ttft_s", "histogram")
    assert export_completeness(registry) == []
    # An observation-only series (never declared) is NOT checked — the
    # contract covers registrations.
    registry.inc("some.ad.hoc.counter")
    assert export_completeness(registry) == []
    # Kind mismatch: declared counter but written via set_gauge — the
    # declaration's zero-fill makes the counters section look populated
    # while the real data ships under a gauge of the same name.
    registry.declare("half.wired", "counter")
    registry.set_gauge("half.wired", 5.0)
    problems = export_completeness(registry)
    assert any("half.wired" in p and "gauge" in p for p in problems), problems


def test_export_completeness_on_global_registry():
    """The real deployment surface: everything obs subsystems declared
    on the process-global registry is fully wired. This is the gate
    that keeps new metrics from shipping half-exported."""
    problems = export_completeness(global_metrics)
    assert problems == [], problems
    declared = global_metrics.declared()
    # And the check is non-vacuous: the new subsystems' series are
    # actually declared there.
    for name in (
        "slo.interactive.attainment", "slo.batch.burn_rate",
        "engine.mfu", "engine.device_busy_frac", "engine.collective_frac",
        "engine.queue_depth",
    ):
        assert name in declared, name


# ---------------------------------------------------------------------- #
# HTTP edge
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_http_slo_class_threading_and_validation():
    from tests.test_server import _request

    from pilottai_tpu.server import APIServer

    server = await APIServer(_mock_handler(latency=0.001)).start()
    try:
        # Body field wins; the flight records the class.
        base = global_metrics.get("slo.batch.requests")
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}],
             "slo_class": "batch"},
        )
        assert status == 200
        assert global_metrics.get("slo.batch.requests") == base + 1
        flights = global_flight.finished()
        assert flights[-1]["attributes"]["slo_class"] == "batch"

        # Unknown class → 400, not silent default.
        status, _, body = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hi"}],
             "slo_class": "turbo"},
        )
        assert status == 400
        assert b"slo_class" in body

        # /slo.json snapshot surface.
        status, _, body = await _request(server.port, "GET", "/slo.json")
        assert status == 200
        snap = json.loads(body)
        assert "interactive" in snap and "batch" in snap
        assert "burn_rate" in snap["batch"]
        assert snap["batch"]["targets"]["ttft_s"] is not None
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_http_slo_class_header_fallback():
    from pilottai_tpu.server import APIServer

    server = await APIServer(_mock_handler(latency=0.001)).start()
    try:
        base = global_metrics.get("slo.batch.requests")
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port
        )
        payload = json.dumps(
            {"messages": [{"role": "user", "content": "hi"}]}
        ).encode()
        writer.write(
            b"POST /v1/chat/completions HTTP/1.1\r\nHost: t\r\n"
            b"x-slo-class: batch\r\n"
            + f"Content-Length: {len(payload)}\r\n".encode()
            + b"Connection: close\r\n\r\n" + payload
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        assert b" 200 " in raw.split(b"\r\n", 1)[0]
        assert global_metrics.get("slo.batch.requests") == base + 1
    finally:
        await server.stop()
