"""Engine tests: handler facade, mock protocol, native engine on CPU jax,
continuous batching behavior."""

import asyncio
import json

import pytest

from pilottai_tpu.core.config import LLMConfig, SamplingConfig
from pilottai_tpu.engine.handler import LLMHandler, RateLimiter, create_backend
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.tokenizer import ByteTokenizer
from pilottai_tpu.engine.types import ChatMessage, GenerationParams, ToolSpec


# --------------------------- tokenizer -------------------------------- #

def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello, TPU world! ünïcodé"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text
    assert tok.vocab_size % 128 == 0


# --------------------------- mock backend ------------------------------ #

@pytest.mark.asyncio
async def test_mock_protocol_detection():
    backend = MockBackend()
    from pilottai_tpu.prompts.manager import PromptManager

    pm = PromptManager("orchestrator")
    prompt = pm.format_prompt("task_analysis", task="do something")
    resp = await backend.generate([ChatMessage(content=prompt)])
    data = json.loads(resp.content)
    assert data["requires_decomposition"] is False
    assert 1 <= data["complexity"] <= 10

    decomp = pm.format_prompt("task_decomposition", task="big job")
    resp = await backend.generate([ChatMessage(content=decomp)])
    subtasks = json.loads(resp.content)["subtasks"]
    assert len(subtasks) == 3 and subtasks[1]["depends_on"] == [0]


@pytest.mark.asyncio
async def test_mock_step_loop_completes():
    backend = MockBackend(steps_to_complete=3)
    from pilottai_tpu.prompts.manager import PromptManager

    pm = PromptManager("agent")
    outputs = []
    for _ in range(5):
        prompt = pm.format_prompt("step_planning", task="Task ID: abc\nwork", history="")
        resp = await backend.generate([ChatMessage(content=prompt)])
        data = json.loads(resp.content)
        outputs.append(data["task_complete"])
        if data["task_complete"]:
            break
    assert outputs == [False, False, True]


@pytest.mark.asyncio
async def test_mock_failure_injection():
    backend = MockBackend(fail_pattern="poison")
    with pytest.raises(RuntimeError):
        await backend.generate([ChatMessage(content="poison pill")])


# --------------------------- handler ----------------------------------- #

@pytest.mark.asyncio
async def test_handler_retries_then_succeeds():
    calls = {"n": 0}

    class Flaky(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return await super().generate(messages, tools, params)

    handler = LLMHandler(
        LLMConfig(provider="mock", retries=3, retry_delay=0.01), backend=Flaky()
    )
    out = await handler.apredict("hello")
    assert out and calls["n"] == 3


@pytest.mark.asyncio
async def test_handler_raises_after_budget():
    class Dead(MockBackend):
        async def generate(self, messages, tools=None, params=None):
            raise RuntimeError("down")

    handler = LLMHandler(
        LLMConfig(provider="mock", retries=1, retry_delay=0.0), backend=Dead()
    )
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        await handler.apredict("hello")


@pytest.mark.asyncio
async def test_rate_limiter_caps_window():
    rl = RateLimiter(max_rpm=3, window=0.2)
    import time

    t0 = time.monotonic()
    for _ in range(4):
        await rl.acquire()
    # 4th acquisition must have waited for the window to roll.
    assert time.monotonic() - t0 >= 0.15


def test_create_backend_unknown_provider():
    with pytest.raises(Exception):
        create_backend(LLMConfig(provider="mock").model_copy(update={"provider": "nope"}))


# --------------------------- native engine (cpu) ------------------------ #

@pytest.mark.asyncio
async def test_native_engine_generates_on_cpu():
    cfg = LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        engine_slots=2,
        engine_max_seq=256,
        sampling=SamplingConfig(max_new_tokens=8, temperature=0.0),
    )
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        resp = await handler.generate_response(
            [ChatMessage(role="user", content="hi")],
            params=GenerationParams(max_new_tokens=8, temperature=0.0),
        )
        assert resp.model == "llama-tiny"
        assert resp.usage.completion_tokens <= 8
        assert resp.finish_reason in ("stop", "length")
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_native_engine_concurrent_requests_batch():
    cfg = LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        engine_slots=4,
        engine_max_seq=256,
    )
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        async def one(i):
            return await handler.generate_response(
                [ChatMessage(content=f"request number {i}")],
                params=GenerationParams(max_new_tokens=6, temperature=0.0),
            )

        responses = await asyncio.gather(*[one(i) for i in range(6)])
        assert len(responses) == 6
        assert all(r.usage.completion_tokens <= 6 for r in responses)
        # Deterministic greedy decoding: identical prompts agree.
        again = await one(3)
        assert again.content == responses[3].content
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_native_engine_tools_in_prompt():
    cfg = LLMConfig(model_name="llama-tiny", provider="cpu", engine_max_seq=256)
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        resp = await handler.generate_response(
            [ChatMessage(content="use tools")],
            tools=[ToolSpec(name="calculator", description="math")],
            params=GenerationParams(max_new_tokens=4),
        )
        assert resp.usage.prompt_tokens > 10
    finally:
        await handler.stop()
