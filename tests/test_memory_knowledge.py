"""Semantic memory (embedding search on device), knowledge manager and
delegation tests."""

import asyncio
import time

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.delegation.delegator import TaskDelegator
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.knowledge.manager import KnowledgeManager
from pilottai_tpu.knowledge.source import CallableSource, FileSource, MemorySource
from pilottai_tpu.memory.embedder import Embedder
from pilottai_tpu.memory.semantic import EnhancedMemory


@pytest.fixture(scope="module")
def embedder():
    return Embedder(model_name="llama-tiny", max_len=64)


# --------------------------- embedder ---------------------------------- #

def test_embedder_shapes_and_normalization(embedder):
    vecs = embedder.encode(["hello world", "completely different text here"])
    assert vecs.shape == (2, embedder.dim)
    import numpy as np

    norms = np.linalg.norm(vecs, axis=1)
    assert np.allclose(norms, 1.0, atol=1e-4)


def test_embedder_similarity_orders_sensibly(embedder):
    import numpy as np

    base = embedder.encode_one("the quarterly financial report shows revenue")
    near = embedder.encode_one("the quarterly financial report shows profit")
    far = embedder.encode_one("zx9!@ qq")
    assert float(base @ near) > float(base @ far)


# --------------------------- semantic memory ---------------------------- #

@pytest.mark.asyncio
async def test_semantic_search_finds_similar(embedder):
    mem = EnhancedMemory(embedder=embedder, capacity=100)
    await mem.store_semantic("revenue grew 20 percent in the fourth quarter")
    await mem.store_semantic("the cat sat on the windowsill all afternoon")
    await mem.store_semantic("profits increased during the final quarter")
    hits = await mem.semantic_search(
        "revenue grew 20 percent in the fourth quarter", limit=2
    )
    assert hits and "quarter" in hits[0]["text"]
    assert hits[0]["score"] >= hits[-1]["score"]


@pytest.mark.asyncio
async def test_semantic_search_tag_and_priority_filters(embedder):
    mem = EnhancedMemory(embedder=embedder, capacity=100)
    await mem.store_semantic("alpha record", tags={"a"}, priority=5)
    await mem.store_semantic("alpha record", tags={"b"}, priority=1)
    hits = await mem.semantic_search("alpha record", tags={"a"})
    assert all("a" in h["tags"] for h in hits)
    hits = await mem.semantic_search("alpha record", min_priority=3)
    assert all(h["priority"] >= 3 for h in hits)


@pytest.mark.asyncio
async def test_keyword_fallback_without_embedder():
    mem = EnhancedMemory(embedder=None)
    await mem.store_semantic("找不到 needle in haystack")
    hits = await mem.semantic_search("NEEDLE")
    assert len(hits) == 1


@pytest.mark.asyncio
async def test_ttl_expiry_and_cleanup(embedder):
    mem = EnhancedMemory(embedder=None)
    await mem.store_semantic("ephemeral", ttl=0.01)
    await mem.store_semantic("durable")
    await asyncio.sleep(0.02)
    assert await mem.semantic_search("ephemeral") == []
    removed = await mem.cleanup()
    assert removed == 1
    assert mem.get_metrics()["semantic_items"] == 1


@pytest.mark.asyncio
async def test_eviction_at_capacity(embedder):
    mem = EnhancedMemory(embedder=embedder, capacity=3)
    for i in range(5):
        await mem.store_semantic(f"record number {i}")
    assert mem.get_metrics()["semantic_items"] == 3
    hits = await mem.semantic_search("record number 4", limit=5)
    assert all(int(h["text"].split()[-1]) >= 2 for h in hits)


@pytest.mark.asyncio
async def test_task_history_versioning_and_patterns():
    mem = EnhancedMemory()
    await mem.store_task("t1", {"phase": "start"})
    await mem.store_task("t1", {"phase": "end"})
    history = await mem.get_task_history("t1")
    assert [h["version"] for h in history] == [0, 1]
    recents = await mem.get_recent_tasks()
    assert recents[0]["phase"] == "end"

    await mem.store_pattern("retry_policy", {"max": 3}, ttl=50)
    assert (await mem.get_pattern("retry_policy"))["max"] == 3
    await mem.store_pattern("stale", 1, ttl=0.001)
    await asyncio.sleep(0.01)
    assert await mem.get_pattern("stale") is None


@pytest.mark.asyncio
async def test_interaction_log_filters():
    mem = EnhancedMemory()
    await mem.log_interaction("a", "b", "hi")
    await mem.log_interaction("b", "c", "yo")
    assert len(await mem.get_interactions("a")) == 1
    assert len(await mem.get_interactions()) == 2


# --------------------------- knowledge ---------------------------------- #

@pytest.mark.asyncio
async def test_knowledge_file_source_and_cache(tmp_path):
    doc = tmp_path / "notes.txt"
    doc.write_text("alpha fact one\nbeta fact two\nalpha fact three\n")
    km = KnowledgeManager(cache_ttl=100)
    await km.add_source(FileSource("notes", doc))
    hits = await km.query_knowledge("alpha")
    assert len(hits) == 2
    await km.query_knowledge("alpha")
    stats = km.get_cache_stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert km.invalidate("alpha@*") == 1


@pytest.mark.asyncio
async def test_knowledge_retry_then_success():
    attempts = {"n": 0}

    def flaky(query):
        attempts["n"] += 1
        if attempts["n"] < 2:
            raise RuntimeError("transient")
        return [{"answer": 42}]

    source = CallableSource("flaky", flaky, retries=2, retry_delay=0.01)
    km = KnowledgeManager()
    await km.add_source(source)
    hits = await km.query_knowledge("anything", use_cache=False)
    assert hits and hits[0]["answer"] == 42
    assert attempts["n"] == 2


@pytest.mark.asyncio
async def test_knowledge_memory_source(embedder):
    mem = EnhancedMemory(embedder=embedder, capacity=50)
    await mem.store_semantic("kubernetes cluster configuration guide")
    km = KnowledgeManager()
    await km.add_source(MemorySource("memory", mem))
    hits = await km.query_knowledge("kubernetes cluster configuration guide")
    assert hits and hits[0]["source"] == "memory"


@pytest.mark.asyncio
async def test_knowledge_unknown_source():
    km = KnowledgeManager()
    with pytest.raises(KeyError):
        await km.query_knowledge("x", sources=["ghost"])


# --------------------------- delegation --------------------------------- #

def make_agent(**cfg_kwargs):
    return BaseAgent(
        config=AgentConfig(**cfg_kwargs),
        llm=LLMHandler(LLMConfig(provider="mock")),
    )


@pytest.mark.asyncio
async def test_delegation_gates():
    manager = make_agent(role="manager", delegation_enabled=True,
                         max_task_complexity=3)
    child = make_agent(role="worker")
    await child.start()
    manager.add_child_agent(child)
    delegator = TaskDelegator(manager)

    simple = Task(description="easy", complexity=1)
    target, reason = await delegator.evaluate_delegation(simple)
    assert target is None and "self-execution" in reason

    complex_task = Task(description="hard", complexity=8)
    target, reason = await delegator.evaluate_delegation(complex_task)
    assert target is child and "complexity" in reason


@pytest.mark.asyncio
async def test_delegation_disabled():
    manager = make_agent(role="manager", delegation_enabled=False)
    delegator = TaskDelegator(manager)
    target, reason = await delegator.evaluate_delegation(
        Task(description="x", complexity=9)
    )
    assert target is None and "disabled" in reason


@pytest.mark.asyncio
async def test_delegation_prefers_historically_successful():
    manager = make_agent(role="manager", delegation_enabled=True,
                         max_task_complexity=2)
    good, bad = make_agent(role="w1"), make_agent(role="w2")
    await good.start(); await bad.start()
    manager.add_child_agent(good); manager.add_child_agent(bad)
    delegator = TaskDelegator(manager)
    for _ in range(5):
        await delegator.record_delegation(good.id, Task(description="x", type="etl"),
                                          success=True, execution_time=1.0)
        await delegator.record_delegation(bad.id, Task(description="x", type="etl"),
                                          success=False, execution_time=1.0,
                                          error="ValueError: boom")
    task = Task(description="new etl", type="etl", complexity=5)
    target, _ = await delegator.evaluate_delegation(task)
    assert target is good
    metrics = delegator.get_metrics()
    assert metrics[bad.id]["errors_by_type"]["ValueError"] == 5


@pytest.mark.asyncio
async def test_delegation_history_cleanup():
    manager = make_agent(role="m", delegation_enabled=True)
    delegator = TaskDelegator(manager, history_retention=0.01)
    await delegator.record_delegation("a1", Task(description="x"), success=True)
    await asyncio.sleep(0.02)
    assert await delegator.cleanup_history() == 1


@pytest.mark.asyncio
async def test_agent_grounds_from_memory_without_hand_built_tools():
    """VERDICT r4 #5: memory= on BaseAgent is no longer a dead parameter —
    memory_search auto-registers and step planning sees retrieved context."""
    from pilottai_tpu.engine.mock import MockBackend

    memory = EnhancedMemory()
    await memory.store_semantic(
        "Risks: vendor delivery slipped two weeks in May",
        tags={"extract"},
    )
    await memory.store_semantic(
        "Findings: revenue grew 12% quarter over quarter",
        tags={"extract"},
    )

    def responder(prompt):
        if '"task_complete"' not in prompt:
            return None
        if "step 0:" in prompt:
            return {"task_complete": True, "action": "respond",
                    "arguments": {}, "reasoning": "done"}
        return {"task_complete": False, "action": "memory_search",
                "arguments": {"query": "revenue findings"},
                "reasoning": "ground the answer"}

    backend = MockBackend(responders=[responder])
    agent = BaseAgent(
        config=AgentConfig(role="analyst", max_iterations=3),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=backend),
        memory=memory,  # no hand-built tools
    )
    # The tool auto-registered.
    assert "memory_search" in agent.tools.names()
    result = await agent.execute_task(
        Task(description="summarize the revenue findings")
    )
    assert result.success
    # The tool's result (retrieved memory text) became the output.
    assert any("revenue grew 12%" in str(s) for s in result.output)
    # Step-planning prompts carried retrieved-memory grounding.
    step_prompts = [c for c in backend.calls if '"task_complete"' in c]
    assert any("relevant memory:" in p for p in step_prompts)


@pytest.mark.asyncio
async def test_agent_knowledge_query_auto_tool():
    from pilottai_tpu.engine.mock import MockBackend

    km = KnowledgeManager()
    await km.add_source(CallableSource(
        "facts", lambda q: [{"fact": f"answer to {q}"}]
    ))

    def responder(prompt):
        if '"task_complete"' not in prompt:
            return None
        if "step 0:" in prompt:
            return {"task_complete": True, "action": "respond",
                    "arguments": {}, "reasoning": "done"}
        return {"task_complete": False, "action": "knowledge_query",
                "arguments": {"query": "the policy"},
                "reasoning": "consult knowledge"}

    agent = BaseAgent(
        config=AgentConfig(role="analyst", max_iterations=3),
        llm=LLMHandler(LLMConfig(provider="mock"),
                       backend=MockBackend(responders=[responder])),
        knowledge=km,
    )
    assert "knowledge_query" in agent.tools.names()
    result = await agent.execute_task(Task(description="what is the policy"))
    assert result.success
    assert any("answer to the policy" in str(r) for r in result.output)


@pytest.mark.asyncio
async def test_user_tool_name_wins_over_auto_registration():
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.tools.tool import Tool

    memory = EnhancedMemory()
    custom = Tool(name="memory_search", function=lambda: "custom",
                  description="user-supplied")
    agent = BaseAgent(
        config=AgentConfig(role="x"),
        llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        tools=[custom],
        memory=memory,
    )
    assert agent.tools.get("memory_search") is custom


@pytest.mark.asyncio
async def test_shared_registry_not_mutated_by_grounding():
    """Two agents sharing one ToolRegistry must each get a memory_search
    bound to THEIR memory, and the caller's registry must stay
    untouched (code-review r5)."""
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.tools.tool import Tool, ToolRegistry

    shared = ToolRegistry([Tool(name="noop", function=lambda: "x")])
    mem_a, mem_b = EnhancedMemory(), EnhancedMemory()
    await mem_a.store_semantic("fact alpha", tags={"t"})
    await mem_b.store_semantic("fact beta", tags={"t"})

    def mk(mem):
        return BaseAgent(
            config=AgentConfig(role="x"),
            llm=LLMHandler(LLMConfig(provider="mock"),
                           backend=MockBackend()),
            tools=shared, memory=mem,
        )

    a, b = mk(mem_a), mk(mem_b)
    assert "memory_search" not in shared  # caller registry untouched
    out_a = await a.tools.get("memory_search").execute({"query": "fact"})
    out_b = await b.tools.get("memory_search").execute({"query": "fact"})
    assert out_a == ["fact alpha"]
    assert out_b == ["fact beta"]
