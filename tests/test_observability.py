"""Observability layer tests: flight recorder, step ring, trace-id
propagation, Prometheus/Perfetto exporters and black-box dumps
(pilottai_tpu/obs + the metrics/tracing/logging satellites)."""

import asyncio
import json
import logging
import re
import time
from collections import deque

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.obs import (
    FlightRecorder,
    StepRing,
    global_blackbox,
    global_flight,
    global_steps,
    metrics_snapshot,
    perfetto_trace,
    phase_summary,
    prometheus_text,
)
from pilottai_tpu.reliability import DeadlineExceeded, inject
from pilottai_tpu.server import APIServer
from pilottai_tpu.utils.metrics import MetricsRegistry, _Histogram
from pilottai_tpu.utils.tracing import Tracer, global_tracer

from tests.test_server import _request


def _mock_handler(**mock_kwargs) -> LLMHandler:
    return LLMHandler(
        LLMConfig(provider="mock", model_name="mock-1"),
        backend=MockBackend(**mock_kwargs),
    )


# ---------------------------------------------------------------------- #
# Satellite: metrics fixes
# ---------------------------------------------------------------------- #


def test_rate_sliding_window_vs_all_time():
    """rate() defaults to a trailing window: a counter whose traffic all
    landed recently reports CURRENT throughput, not counter ÷ uptime."""
    reg = MetricsRegistry()
    reg._started = time.time() - 1000.0  # long-idle process
    reg.inc("win.counter", 100)          # burst arriving now
    legacy = reg.rate("win.counter", window=None)
    recent = reg.rate("win.counter", window=60.0)
    assert legacy < 0.2                  # 100 / ~1000 s — the old bug
    assert recent > 1.0                  # 100 / 60 s — actual throughput

    # Traffic that STOPPED also reads as stopped: age the events past
    # the window and the rate returns to ~0 instead of a stale average.
    reg._events["win.counter"] = deque(
        (ts - 200.0, cum) for ts, cum in reg._events["win.counter"]
    )
    assert reg.rate("win.counter", window=60.0) == 0.0


def test_rate_young_registry_divides_by_age():
    reg = MetricsRegistry()
    reg.inc("young", 10)
    # Registry is ~0 s old: dividing by the full 60 s window would
    # underreport; dividing by age reports the actual burst rate.
    assert reg.rate("young", window=60.0) > 10.0


def test_histogram_percentiles_are_window_aware():
    h = _Histogram(max_samples=100)
    for v in range(1000):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 1000            # all-time
    assert s["window"] == 100            # percentile basis
    # Only the most recent 100 samples (900..999) back the percentiles —
    # the old rotating-index eviction left arbitrary-aged values mixed in.
    assert s["p50"] >= 900
    assert s["p99"] >= 990
    assert h.percentile(0) >= 900


# ---------------------------------------------------------------------- #
# Tracer: parentage, explicit trace ids, direct emission
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_tracer_parentage_under_interleaved_tasks():
    """Two asyncio tasks interleaving awaits inside nested spans must
    each see their OWN stack: children parent to their task's root, and
    the two tasks' trace ids stay distinct."""
    tracer = Tracer()
    roots = {}

    async def worker(name):
        with tracer.span(f"root.{name}") as root:
            roots[name] = root
            await asyncio.sleep(0.01)
            with tracer.span(f"child.{name}") as child:
                await asyncio.sleep(0.01)
                assert child.parent_id == root.span_id
                assert child.trace_id == root.trace_id

    await asyncio.gather(worker("a"), worker("b"))
    assert roots["a"].trace_id != roots["b"].trace_id
    for name in ("a", "b"):
        child = tracer.finished(f"child.{name}")[0]
        assert child.parent_id == roots[name].span_id


def test_tracer_explicit_trace_id_and_emit():
    tracer = Tracer()
    with tracer.span("root", trace_id="fixed-id") as root:
        # A nested span inherits the parent's trace even when handed a
        # different explicit id — one request, one trace.
        with tracer.span("child", trace_id="other-id") as child:
            pass
    assert root.trace_id == "fixed-id"
    assert child.trace_id == "fixed-id"

    emitted = tracer.emit(
        "engine.batch_decode", trace_id="fixed-id",
        parent_id=child.span_id, start=child.start, end=child.end or 0.0,
        tokens=4,
    )
    spans = tracer.for_trace("fixed-id")
    assert {s.name for s in spans} == {"root", "child", "engine.batch_decode"}
    assert emitted.attributes["tokens"] == 4


# ---------------------------------------------------------------------- #
# Exporters
# ---------------------------------------------------------------------- #


def test_perfetto_export_round_trip():
    tracer = Tracer()
    with tracer.span("server.request", trace_id="pft-1"):
        with tracer.span("engine.generate"):
            time.sleep(0.002)
    ring = StepRing()
    ring.record("engine.chunk", tokens=7, slots_active=2, queue_depth=0,
                kv_pages_free=10)
    ring.record("engine.admit", n=2, slots_active=2, queue_depth=1)

    doc = json.loads(json.dumps(  # round-trip: valid trace_event JSON
        perfetto_trace(tracer.for_trace("pft-1"), steps=ring.snapshot())
    ))
    events = doc["traceEvents"]
    slices = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in slices} == {"server.request", "engine.generate"}
    for e in slices:
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
    # Nesting preserved: the child slice is contained in the parent's
    # [ts, ts+dur] on the same track — how Perfetto reconstructs trees.
    parent = next(e for e in slices if e["name"] == "server.request")
    child = next(e for e in slices if e["name"] == "engine.generate")
    assert child["tid"] == parent["tid"]
    assert child["ts"] >= parent["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1e-3
    # Engine steps ride along as counter tracks.
    counters = [e for e in events if e["ph"] == "C"]
    assert any(e["name"] == "engine/tokens" for e in counters)


def test_prometheus_exposition_parseable():
    reg = MetricsRegistry()
    reg.inc("engine.requests", 5)
    reg.set_gauge("engine.slots_active", 3)
    for v in (0.1, 0.2, 0.3):
        reg.observe("request.ttft_s", v)
    text = prometheus_text(
        metrics_snapshot(component={"requests": 5, "nested": {"x": 1.5}},
                         registry=reg)
    )
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+$"
    )
    lines = [ln for ln in text.strip().split("\n") if ln]
    assert lines
    for line in lines:
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4 and parts[3] in (
                "counter", "gauge", "summary"
            ), line
        else:
            assert sample.match(line), line
    assert 'pilottai_request_ttft_s{quantile="0.5"}' in text
    assert "pilottai_request_ttft_s_count 3.0" in text
    assert "pilottai_engine_requests 5.0" in text
    assert "pilottai_component_nested_x 1.5" in text


# ---------------------------------------------------------------------- #
# Flight recorder
# ---------------------------------------------------------------------- #


def test_flight_recorder_phase_ledger():
    reg = MetricsRegistry()
    rec = FlightRecorder(registry=reg)
    rec.start("f1", model="m")
    rec.mark("f1", "admitted")
    rec.token("f1", 1)
    time.sleep(0.005)
    rec.token("f1", 4)
    summary = rec.finish("f1", "ok")
    assert summary["tokens"] == 5
    assert summary["queue_wait_s"] >= 0
    assert summary["ttft_s"] >= 0
    assert summary["tpot_s"] > 0
    hists = reg.snapshot()["histograms"]
    for name in ("request.ttft_s", "request.tpot_s", "request.itl_s",
                 "request.e2e_s", "request.queue_wait_s"):
        assert hists[name]["count"] >= 1, name
    # Double-finish and unknown ids are safe no-ops.
    assert rec.finish("f1") is None
    assert rec.finish("never-started") is None
    rec.token("never-started", 3)
    # The finished ring still describes the flight for dumps.
    assert rec.describe("f1")["status"] == "ok"


@pytest.mark.asyncio
async def test_concurrent_same_trace_calls_get_separate_flights():
    """Orchestrator fan-out: concurrent engine calls sharing one ambient
    trace must keep SEPARATE phase ledgers (flight_id), not merge into
    one blended TTFT/e2e record (review regression)."""
    handler = _mock_handler(latency=0.01)
    with global_tracer.span("serve.execute_task", trace_id="fanout-t1"):
        await asyncio.gather(*[
            handler.apredict(f"subtask {i}") for i in range(3)
        ])
    flights = [
        r for r in global_flight.finished() if r["trace_id"] == "fanout-t1"
    ]
    assert len(flights) == 3
    assert len({f["flight_id"] for f in flights}) == 3
    assert all(f["status"] == "ok" and f["tokens"] >= 1 for f in flights)


def test_failed_flights_do_not_pollute_latency_histograms():
    """Shed/fast-fail flights are counted, not timed: an overload storm
    of ~0 ms sheds must not drag the window-aware e2e percentiles toward
    zero mid-outage (review regression)."""
    reg = MetricsRegistry()
    rec = FlightRecorder(registry=reg)
    rec.start("ok-1")
    rec.token("ok-1", 2)
    time.sleep(0.002)
    rec.finish("ok-1", "ok")
    for i in range(50):
        rec.start(f"shed-{i}")
        rec.finish(f"shed-{i}", "shed")
    snap = reg.snapshot()
    assert snap["histograms"]["request.e2e_s"]["count"] == 1  # ok only
    assert snap["counters"]["request.failed"] == 50
    assert snap["counters"]["request.finished.shed"] == 50


def test_step_ring_bounded_and_ordered():
    ring = StepRing(capacity=8)
    for i in range(20):
        ring.record("engine.chunk", tokens=i)
    snap = ring.snapshot()
    assert len(snap) == 8 and len(ring) == 8
    assert [r["tokens"] for r in snap] == list(range(12, 20))
    assert snap[-1]["seq"] == 20
    assert ring.snapshot(3) == snap[-3:]


@pytest.mark.asyncio
async def test_ttft_tpot_percentiles_from_mock_engine_run():
    """A mock-engine run (no batcher, envelope-synthesized tokens) still
    yields TTFT/TPOT percentile surfaces from MetricsRegistry."""
    from pilottai_tpu.utils.metrics import global_metrics

    # Isolate the shared global registry: drop the request-phase
    # histograms up front so the window holds (at least) this test's 4
    # flights, independent of suite order. Lower bound, not exact: the
    # reset isolates PAST tests, but a straggler flight from an earlier
    # async test (a server draining in the background) can legitimately
    # finish after the reset and land in this window — an exact ==4
    # flaked under load for exactly that reason.
    global_metrics.reset_histograms("request.")
    handler = _mock_handler(latency=0.002)
    for i in range(4):
        await handler.apredict(f"measure ttft {i}")
    hists = global_metrics.snapshot()["histograms"]
    for name in ("request.ttft_s", "request.tpot_s", "request.e2e_s"):
        assert hists[name]["count"] >= 4, name
        assert hists[name]["p50"] is not None
        assert hists[name]["p99"] is not None
    assert phase_summary()["ttft"]["p50_ms"] is not None


# ---------------------------------------------------------------------- #
# HTTP edge: trace ids, unified snapshot, Prometheus format
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_stream_flight_status_unpoisoned_by_handled_exception():
    """A successful astream consumed INSIDE an except block must finish
    its flight as ok: an async generator's finally can see the consumer
    frame's already-handled exception via sys.exc_info(), which used to
    misclassify the retry as a deadline failure (review regression)."""
    handler = _mock_handler(script=["first try", "retry works"])
    params = GenerationParams(trace_id="retry-after-deadline-1")
    try:
        raise DeadlineExceeded("first attempt blew its budget")
    except DeadlineExceeded:
        # Retry while the handled exception is still "current".
        chunks = [d async for d in handler.astream(
            "retry please", params=params.model_copy(
                update={"trace_id": "retry-after-deadline-2"}
            ),
        )]
    assert "".join(chunks)
    flight = next(
        r for r in reversed(global_flight.finished())
        if r["trace_id"] == "retry-after-deadline-2"
    )
    assert flight["status"] == "ok"
    # And no spurious deadline dump was recorded for it.
    assert not any(
        r["trace_id"] == "retry-after-deadline-2"
        for r in global_blackbox.recent()
    )


@pytest.mark.asyncio
async def test_ambient_span_trace_adopted_for_direct_calls():
    """Orchestrator-driven engine calls (no HTTP edge) join the ambient
    span's trace instead of splitting the request across two ids."""
    handler = _mock_handler()
    with global_tracer.span("serve.execute_task", trace_id="ambient-t1"):
        await handler.apredict("do the thing")
    names = {s.name for s in global_tracer.for_trace("ambient-t1")}
    assert "engine.generate" in names
    assert any(
        r["trace_id"] == "ambient-t1" for r in global_flight.finished()
    )


@pytest.mark.asyncio
async def test_server_request_id_roundtrip_and_span_tree():
    server = await APIServer(_mock_handler()).start()
    try:
        status, hdrs, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hello"}]},
        )
        assert status == 200
        rid = hdrs["x-request-id"]  # server minted one
        assert re.fullmatch(r"[0-9a-f]{16}", rid)

        # Client-supplied ids are accepted and echoed...
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port
        )
        body = json.dumps(
            {"messages": [{"role": "user", "content": "hi"}]}
        ).encode()
        writer.write(
            f"POST /v1/chat/completions HTTP/1.1\r\nHost: t\r\n"
            f"x-request-id: my-req.01\r\nContent-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        assert b"x-request-id: my-req.01" in raw

        # ...and the span tree nests server.request -> engine.generate
        # under that exact trace id.
        spans = global_tracer.for_trace("my-req.01")
        root = next(s for s in spans if s.name == "server.request")
        gen = next(s for s in spans if s.name == "engine.generate")
        assert root.parent_id is None
        assert gen.parent_id == root.span_id

        # A hostile header (newline injection, oversize) is replaced.
        status, hdrs, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}]},
            token=None,
        )
        assert status == 200
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_server_metrics_unified_and_prometheus():
    server = await APIServer(_mock_handler()).start()
    try:
        await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "warm the metrics"}]},
        )
        # JSON: the unified snapshot shape (dashboard parity) + the
        # back-compat "handler" alias.
        status, _, body = await _request(server.port, "GET", "/metrics")
        assert status == 200
        snap = json.loads(body)
        assert {"uptime_s", "counters", "gauges", "histograms",
                "component", "handler"} <= set(snap)
        assert snap["handler"] == snap["component"]

        # Prometheus: parseable and carrying the ttft/tpot summaries.
        status, hdrs, body = await _request(
            server.port, "GET", "/metrics?format=prometheus"
        )
        assert status == 200
        assert hdrs["content-type"].startswith("text/plain")
        text = body.decode()
        assert "pilottai_request_ttft_s" in text
        assert "pilottai_request_tpot_s" in text
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+$"
        )
        for line in text.strip().split("\n"):
            assert line.startswith("# TYPE ") or sample.match(line), line
    finally:
        await server.stop()


def test_dashboard_prometheus_and_trace_export():
    import urllib.request

    from pilottai_tpu.utils.dashboard import MetricsDashboard
    from pilottai_tpu.utils.metrics import global_metrics

    global_metrics.inc("dash.obs_counter", 2)
    with global_tracer.span("server.request", trace_id="dash-trace-1"):
        with global_tracer.span("engine.generate"):
            pass
    d = MetricsDashboard(port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{d.port}/metrics.json?format=prometheus",
            timeout=10,
        ) as r:
            text = r.read().decode()
            assert r.headers.get_content_type() == "text/plain"
        assert "pilottai_dash_obs_counter" in text

        with urllib.request.urlopen(
            f"http://127.0.0.1:{d.port}/trace.json?trace_id=dash-trace-1",
            timeout=10,
        ) as r:
            doc = json.loads(r.read())
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert names == {"server.request", "engine.generate"}
    finally:
        d.stop()


# ---------------------------------------------------------------------- #
# Satellite: structured log correlation
# ---------------------------------------------------------------------- #


def test_log_records_carry_trace_id_from_active_span():
    from pilottai_tpu.utils.logging import JsonFormatter

    fmt = JsonFormatter()

    def make_record():
        return logging.LogRecord(
            "pilottai_tpu.engine.handler", logging.INFO, __file__, 1,
            "retrying request", (), None,
        )

    with global_tracer.span("server.request", trace_id="log-trace-9"):
        line = json.loads(fmt.format(make_record()))
    assert line["trace_id"] == "log-trace-9"
    # Outside any span: no trace_id key, no crash.
    line = json.loads(fmt.format(make_record()))
    assert "trace_id" not in line


# ---------------------------------------------------------------------- #
# Black-box dumps
# ---------------------------------------------------------------------- #


def test_breaker_open_fires_blackbox_hook():
    from pilottai_tpu.reliability import CircuitBreaker

    opened = []
    breaker = CircuitBreaker(failure_threshold=2, name="bb-test")
    breaker.on_open = opened.append
    breaker.record_failure()
    assert opened == []
    breaker.record_failure()
    assert opened == ["bb-test"]
    # The handler wires the hook to the black-box dumper by default.
    handler = _mock_handler()
    assert handler.breaker is not None and handler.breaker.on_open is not None


@pytest.mark.asyncio
async def test_blackbox_dump_on_injected_deadline_fault(tmp_path):
    """Acceptance path: a request through APIServer under an injected
    ``handler.timeout`` fault expires its deadline and leaves a journal
    black-box dump — last engine steps + the request's trace id."""
    from pilottai_tpu.checkpoint.journal import BlackBoxJournal

    dump_path = tmp_path / "blackbox.jsonl"
    global_blackbox.configure(str(dump_path))
    server = await APIServer(_mock_handler()).start()
    try:
        # A healthy request first: populates the step ring, so the dump
        # has engine history to replay.
        status, _, _ = await _request(
            server.port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "healthy one"}]},
        )
        assert status == 200
        assert any(
            r["kind"] == "handler.request" for r in global_steps.snapshot()
        )

        with inject("handler.timeout", exc=asyncio.TimeoutError, times=None):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port
            )
            body = json.dumps({
                "messages": [{"role": "user", "content": "doomed"}],
                "timeout": 0.25,
            }).encode()
            writer.write(
                f"POST /v1/chat/completions HTTP/1.1\r\nHost: t\r\n"
                f"x-request-id: doomed-req-1\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body
            )
            await writer.drain()
            raw = await reader.read()
            writer.close()
            await writer.wait_closed()
        status = int(raw.split(b" ", 2)[1])
        assert status == 408  # deadline exceeded -> timeout_error

        global_blackbox.flush()  # journal writes ride a background thread
        records = BlackBoxJournal.read(dump_path)
        dumps = [r for r in records if r["trace_id"] == "doomed-req-1"]
        assert dumps, records
        dump = dumps[0]
        assert dump["reason"] == "deadline_expired"
        assert dump["ev"] == "blackbox"
        # Last engine steps captured (the healthy request's handler step
        # at minimum) and the flight ledger closed as deadline.
        assert any(s["kind"] == "handler.request" for s in dump["steps"])
        assert dump["flight"]["status"] == "deadline"
        # The dump's span list is the request's own tree.
        assert all(s["trace_id"] == "doomed-req-1" for s in dump["spans"])

        # Deduplication: the same (reason, trace) never dumps twice.
        assert global_blackbox.dump(
            "deadline_expired", trace_id="doomed-req-1"
        ) is None
    finally:
        await server.stop()
        global_blackbox.disable()


# ---------------------------------------------------------------------- #
# Native CPU engine: real TTFT/ITL marks, batcher span, expiry dump
# ---------------------------------------------------------------------- #


@pytest.mark.asyncio
async def test_native_engine_span_tree_ring_and_expiry_dump(tmp_path):
    """One CPU-engine boot covers the native-path story: server →
    handler → batcher span nesting under one x-request-id, real
    token-level flight marks, engine.chunk ring records, and a
    mid-decode deadline expiry black-box dump from the batcher."""
    from pilottai_tpu.engine.batcher import GenRequest

    global_blackbox.configure(str(tmp_path / "native_blackbox.jsonl"))
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=2, engine_max_seq=128, engine_chunk=4,
    ))
    server = await APIServer(handler).start()
    try:
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port
        )
        body = json.dumps({
            "messages": [{"role": "user", "content": "trace me"}],
            "max_tokens": 12, "temperature": 0,
        }).encode()
        writer.write(
            f"POST /v1/chat/completions HTTP/1.1\r\nHost: t\r\n"
            f"x-request-id: native-trace-1\r\n"
            f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            .encode() + body
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        assert int(raw.split(b" ", 2)[1]) == 200

        # Span tree: server.request -> engine.generate -> engine.batch_decode,
        # the batcher's span emitted from its reader thread with an
        # explicit parent.
        spans = global_tracer.for_trace("native-trace-1")
        root = next(s for s in spans if s.name == "server.request")
        gen = next(s for s in spans if s.name == "engine.generate")
        batch = next(s for s in spans if s.name == "engine.batch_decode")
        assert gen.parent_id == root.span_id
        assert batch.parent_id == gen.span_id
        assert batch.attributes["tokens"] >= 1

        # Perfetto export of the full tree stays loadable JSON.
        doc = json.loads(json.dumps(perfetto_trace(
            spans, steps=global_steps.snapshot()
        )))
        assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 3

        # The ring saw real engine activity.
        kinds = {r["kind"] for r in global_steps.snapshot()}
        assert {"engine.admit", "engine.chunk"} <= kinds
        chunk = next(
            r for r in reversed(global_steps.snapshot())
            if r["kind"] == "engine.chunk"
        )
        assert {"slots_active", "tokens", "queue_depth",
                "pipeline_depth", "page_strip"} <= set(chunk)

        # Real token-level phases (not envelope-synthesized): the flight
        # recorded admission and per-token marks from the batcher.
        flight = next(
            r for r in reversed(global_flight.finished())
            if r["trace_id"] == "native-trace-1"
        )
        assert flight["status"] == "ok"
        assert flight["tokens"] >= 1
        assert "queue_wait_s" in flight and "ttft_s" in flight
        assert "admitted" in flight["marks"]

        # Mid-decode expiry: submit straight to the batcher (bypassing
        # the handler's own deadline watchdog) with a chunk dispatch
        # slowed past the deadline — the device loop's sweep must
        # force-release the slot, emit the span and write the dump.
        batcher = handler.backend.batcher
        req = GenRequest(
            prompt_ids=list(range(2, 34)), max_new_tokens=64,
            deadline=time.monotonic() + 0.25,
            trace_id="native-expired-1",
        )
        with inject("engine.step", delay=0.6, times=1):
            fut = batcher.submit(req)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=30)
        deadline = time.monotonic() + 10
        dump = None
        while dump is None and time.monotonic() < deadline:
            dump = next(
                (r for r in global_blackbox.recent()
                 if r["trace_id"] == "native-expired-1"), None,
            )
            await asyncio.sleep(0.05)
        assert dump is not None
        assert dump["reason"] == "deadline_expired"
        assert any(s["kind"] == "engine.chunk" for s in dump["steps"])
    finally:
        await server.stop()
        await handler.stop()
        global_blackbox.disable()
