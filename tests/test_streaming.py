"""Streaming generation: incremental detokenization, the batcher's
``on_tokens`` fold hook, and the ``astream`` facade.

The reference has no streaming surface at all (its engine is one remote
HTTP call, ``pilott/engine/llm.py:59``); this is native-engine API the
in-tree batcher makes natural — tokens already surface chunk-by-chunk on
the host, streaming just forwards each fold to the consumer.
"""

import asyncio

import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.engine.tokenizer import ByteTokenizer, IncrementalDecoder
from pilottai_tpu.engine.types import ChatMessage, GenerationParams


# ----------------------- incremental decoder --------------------------- #

def test_incremental_decoder_matches_full_decode():
    tok = ByteTokenizer()
    text = "hello, TPU wörld — ünïcodé ✓"
    ids = tok.encode(text, add_bos=False)
    dec = IncrementalDecoder(tok)
    out = ""
    for i in ids:  # worst case: one byte at a time
        out += dec.push([i])
    out += dec.flush()
    assert out == text


def test_incremental_decoder_holds_partial_utf8():
    tok = ByteTokenizer()
    ids = list("é".encode("utf-8"))  # two bytes
    dec = IncrementalDecoder(tok)
    assert dec.push([ids[0]]) == ""  # partial sequence withheld
    assert dec.push([ids[1]]) == "é"
    assert dec.flush() == ""


def test_incremental_decoder_flush_emits_trailing_partial():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    assert dec.push(list("ab".encode()) + ["é".encode()[0]]) == "ab"
    assert dec.flush() == "�"  # truncated sequence renders as U+FFFD


def test_stop_cut_order_independent():
    from pilottai_tpu.engine.native import _stop_cut

    # Straddling stops: "cd"'s occurrence overlaps "bc"'s cut; the
    # earliest occurrence wins regardless of list order.
    assert _stop_cut("abcd", ["cd", "bc"]) == 1
    assert _stop_cut("abcd", ["bc", "cd"]) == 1
    assert _stop_cut("abcd", ["xy"]) is None
    assert _stop_cut("abcd", []) is None


def test_chat_template_preferred_when_available(tiny_backend):
    """_build_request uses the tokenizer's chat template when it has
    one (add_bos suppressed — templates emit their own BOS text), and
    falls back to the generic transcript otherwise."""
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    msgs = [ChatMessage(role="user", content="hello")]
    params = GenerationParams(max_new_tokens=4)

    tok = tiny_backend.tokenizer
    # Byte tokenizer has no template → generic framing with BOS.
    req = tiny_backend._build_request(msgs, None, params)
    assert req.prompt_ids[0] == tok.bos_id
    assert tok.decode(req.prompt_ids).startswith("<|user|>")

    class Templated(type(tok)):
        def render_chat(self, messages):
            assert messages[-1]["content"] == "hello"
            return "<<TMPL>>" + messages[-1]["content"]

    tiny_backend.tokenizer = Templated()
    try:
        req = tiny_backend._build_request(msgs, None, params)
        assert tok.decode(req.prompt_ids) == "<<TMPL>>hello"
        assert req.prompt_ids[0] != tok.bos_id  # no doubled BOS
        # The tool preamble rides as a system turn through the template.
        from pilottai_tpu.engine.types import ToolSpec

        seen = {}

        class Capture(type(tok)):
            def render_chat(self, messages):
                seen["roles"] = [m["role"] for m in messages]
                return "x"

        tiny_backend.tokenizer = Capture()
        tiny_backend._build_request(
            msgs, [ToolSpec(name="search", description="web")], params
        )
        assert seen["roles"][0] == "system"
    finally:
        tiny_backend.tokenizer = tok


def test_hf_render_chat_returns_none_without_template():
    """An HF tokenizer with no chat_template must return None (never
    guess a format); exercised through a stub with the same surface."""
    from pilottai_tpu.engine.tokenizer import HFTokenizer

    class Stub:
        chat_template = None

    hf = HFTokenizer.__new__(HFTokenizer)
    hf._tok = Stub()
    assert hf.render_chat([{"role": "user", "content": "x"}]) is None

    class WithTemplate:
        chat_template = "{{ messages }}"

        def apply_chat_template(self, messages, tokenize, add_generation_prompt):
            assert tokenize is False and add_generation_prompt is True
            return "RENDERED:" + messages[-1]["content"]

    hf._tok = WithTemplate()
    assert hf.render_chat(
        [{"role": "user", "content": "x"}]
    ) == "RENDERED:x"


# ----------------------- mock backend streaming ------------------------ #

@pytest.mark.asyncio
async def test_mock_stream_concatenates_to_generate():
    backend = MockBackend(script=["alpha beta gamma delta", "alpha beta gamma delta"])
    full = (await backend.generate([ChatMessage(content="x")])).content
    deltas = [
        d async for d in backend.generate_stream([ChatMessage(content="x")])
    ]
    assert len(deltas) > 1
    assert "".join(deltas) == full


@pytest.mark.asyncio
async def test_handler_astream_inactivity_timeout():
    """A wedged backend trips config.timeout instead of pinning the
    concurrency semaphore forever."""
    from pilottai_tpu.engine.base import LLMBackend

    class Wedged(LLMBackend):
        name = "wedged"

        async def generate(self, messages, tools=None, params=None):
            raise AssertionError("unused")

        async def generate_stream(self, messages, tools=None, params=None):
            await asyncio.sleep(3600)
            yield ""

    handler = LLMHandler(
        LLMConfig(provider="mock", timeout=0.05), backend=Wedged()
    )
    with pytest.raises(asyncio.TimeoutError):
        async for _ in handler.astream("hello"):
            pass
    # Semaphore released: a healthy backend call still goes through.
    handler.backend = MockBackend(script=["ok"])
    assert [d async for d in handler.astream("x")] == ["ok"]


@pytest.mark.asyncio
async def test_handler_astream_mock():
    handler = LLMHandler(
        LLMConfig(provider="mock"),
        backend=MockBackend(script=["one two three"]),
    )
    deltas = [d async for d in handler.astream("hello")]
    assert "".join(deltas) == "one two three"


# ----------------------- native engine streaming ----------------------- #

@pytest.fixture(scope="module")
def tiny_backend():
    """Module-shared native engine (threads + concurrent.futures — safe
    across the per-test event loops, unlike asyncio primitives)."""
    from pilottai_tpu.engine.native import NativeEngine

    backend = NativeEngine(
        LLMConfig(
            model_name="llama-tiny",
            provider="cpu",
            engine_slots=2,
            engine_max_seq=256,
            engine_chunk=4,  # several folds per request → several deltas
        ),
        platform="cpu",
    )
    yield backend
    asyncio.run(backend.stop())


@pytest.fixture()
def tiny_handler(tiny_backend):
    """Fresh facade per test: the handler's semaphore binds to the
    running loop on first use and each test gets its own loop."""
    return LLMHandler(
        LLMConfig(model_name="llama-tiny", provider="cpu"),
        backend=tiny_backend,
    )


@pytest.mark.asyncio
async def test_native_stream_matches_generate(tiny_handler):
    params = GenerationParams(max_new_tokens=24, temperature=0.0)
    msgs = [ChatMessage(content="stream parity prompt")]
    full = (await tiny_handler.generate_response(msgs, params=params)).content
    deltas = [d async for d in tiny_handler.astream(msgs, params=params)]
    assert "".join(deltas) == full
    # Chunked fold granularity: a 24-token reply over chunk=4 must
    # surface across several folds (byte tokenizer: ≥1 char per token).
    assert len(deltas) > 1


@pytest.mark.asyncio
async def test_native_stream_stop_string(tiny_handler):
    params = GenerationParams(max_new_tokens=24, temperature=0.0)
    msgs = [ChatMessage(content="stream parity prompt")]
    full = (await tiny_handler.generate_response(msgs, params=params)).content
    if len(full) < 4:
        pytest.skip("reply too short to carve a stop string from")
    stop = full[2:4]
    params2 = params.model_copy(update={"stop": [stop]})
    expect = (
        await tiny_handler.generate_response(msgs, params=params2)
    ).content
    assert expect == full[: full.find(stop)]
    deltas = [d async for d in tiny_handler.astream(msgs, params=params2)]
    assert "".join(deltas) == expect


@pytest.mark.asyncio
async def test_native_stream_multi_stop_parity(tiny_handler):
    """Multiple stop strings truncate iteratively in list order, exactly
    like generate() — the stream must not retain a later-listed stop."""
    params = GenerationParams(max_new_tokens=24, temperature=0.0)
    msgs = [ChatMessage(content="stream parity prompt")]
    full = (await tiny_handler.generate_response(msgs, params=params)).content
    if len(full) < 6:
        pytest.skip("reply too short to carve two stop strings from")
    stops = [full[4:6], full[1:3]]  # second stop cuts EARLIER than first
    params2 = params.model_copy(update={"stop": stops})
    expect = (
        await tiny_handler.generate_response(msgs, params=params2)
    ).content
    deltas = [d async for d in tiny_handler.astream(msgs, params=params2)]
    assert "".join(deltas) == expect


@pytest.mark.asyncio
async def test_native_stream_overlapping_stops_parity(tiny_handler):
    """A longer stop that STARTS earlier but COMPLETES later than a
    shorter stop must still win: the cut is the earliest occurrence of
    any stop, exactly generate()'s net truncation."""
    params = GenerationParams(max_new_tokens=24, temperature=0.0)
    msgs = [ChatMessage(content="stream parity prompt")]
    full = (await tiny_handler.generate_response(msgs, params=params)).content
    if len(full) < 8:
        pytest.skip("reply too short to carve overlapping stops from")
    stops = [full[1:7], full[4:6]]  # long starts at 1, short inside it
    params2 = params.model_copy(update={"stop": stops})
    # Expected = generate_response's own truncation for the SAME stops
    # (with repetitive model text the carved stops may occur even
    # earlier than where they were carved — parity, not position, is
    # the claim).
    expect = (
        await tiny_handler.generate_response(msgs, params=params2)
    ).content
    deltas = [d async for d in tiny_handler.astream(msgs, params=params2)]
    assert "".join(deltas) == expect


@pytest.mark.asyncio
async def test_native_stream_early_close_frees_slot(tiny_handler):
    params = GenerationParams(max_new_tokens=64, temperature=0.0)
    agen = tiny_handler.astream(
        [ChatMessage(content="a long reply to abandon")], params=params
    )
    got = None
    async for d in agen:
        got = d
        break  # abandon mid-stream
    await agen.aclose()
    assert got  # saw at least one delta before closing
    # The engine keeps serving: the abandoned request's slot is freed at
    # the next chunk boundary, so a full wave still completes.
    outs = await asyncio.gather(*[
        tiny_handler.apredict(
            f"follow-up {i}",
            params=GenerationParams(max_new_tokens=8, temperature=0.0),
        )
        for i in range(2)
    ])
    assert len(outs) == 2


@pytest.mark.asyncio
async def test_native_stream_with_speculation():
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        engine_slots=2,
        engine_max_seq=256,
        engine_chunk=4,
        engine_speculate=4,
    ))
    try:
        params = GenerationParams(max_new_tokens=16, temperature=0.0)
        msgs = [ChatMessage(content="speculative stream prompt")]
        full = (await handler.generate_response(msgs, params=params)).content
        deltas = [d async for d in handler.astream(msgs, params=params)]
        assert "".join(deltas) == full
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_native_stream_info_matches_generate(tiny_handler):
    """The stream's end-of-stream ``info`` (finish_reason /
    completion_tokens) must agree with what ``generate()`` reports for
    the same request — SSE consumers report truncation from it."""
    msgs = [ChatMessage(content="stream info parity prompt")]
    for max_new in (4, 48):  # 4 almost surely truncates ("length")
        params = GenerationParams(max_new_tokens=max_new, temperature=0.0)
        resp = await tiny_handler.generate_response(msgs, params=params)
        info = {}
        deltas = [
            d async for d in tiny_handler.astream(
                msgs, params=params, info=info
            )
        ]
        assert "".join(deltas) == resp.content
        assert info["finish_reason"] == resp.finish_reason
        assert info["completion_tokens"] == resp.usage.completion_tokens
