"""Subword JSON grammar masking: the token→byte product construction
(VERDICT r2 missing #2 / next-step 5).

The byte automaton (tests/test_json_mask.py) only constrains byte
tokenizers; real checkpoints use subword vocabs. These tests build a
small synthetic multi-byte BPE-style vocab and assert:

* token-level advance == byte-level advance over the same text;
* masked sampling with ADVERSARIAL (random) logits produces 100%%
  parseable JSON for every seed, including multi-byte tokens that cross
  container boundaries;
* budget feasibility: documents always close within max_new_tokens;
* the engine end-to-end serves json_mode with a subword tokenizer
  (native.py no longer gates on ByteTokenizer).
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.json_mask import (
    json_advance,
    json_advance_tokens,
    json_allowed_tokens,
    token_byte_table,
)
from pilottai_tpu.engine.sampling import SamplingState, sample_core, update_slot
from pilottai_tpu.engine.tokenizer import Tokenizer


class TinyBPE(Tokenizer):
    """Synthetic subword tokenizer: all printable ASCII single chars plus
    multi-byte merges chosen to cross JSON structure boundaries."""

    MERGES = [
        '{"', '":', '", "', '"}', '}}', '"]', '], "', ': {', ': [',
        'true', 'false', 'null', '0.', '123', '-1', '1e3',
        'name', 'value', 'key', 'abc', '\\n', '\\"', ', ', '": "',
        "\n",
    ]

    def __init__(self) -> None:
        base = [chr(b) for b in range(0x20, 0x7F)]
        self.pieces = [None, None, None] + base + self.MERGES
        self.pad_id, self.bos_id, self.eos_id = 0, 1, 2
        self.vocab_size = len(self.pieces)
        # Longest-match-first encode order.
        self._by_len = sorted(
            [(p, i) for i, p in enumerate(self.pieces) if p],
            key=lambda t: -len(t[0]),
        )

    def token_bytes(self, i):
        p = self.pieces[i]
        return p.encode() if p else None

    def encode(self, text, add_bos=True):
        ids = []
        pos = 0
        while pos < len(text):
            for p, i in self._by_len:
                if text.startswith(p, pos):
                    ids.append(i)
                    pos += len(p)
                    break
            else:
                pos += 1  # unencodable char: drop
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids):
        return "".join(self.pieces[i] or "" for i in ids)


@pytest.fixture(scope="module")
def tok_tables():
    tok = TinyBPE()
    tb, tl = token_byte_table(tok)
    return tok, jnp.asarray(tb), jnp.asarray(tl)


def test_table_excludes_specials_and_keeps_merges(tok_tables):
    tok, tb, tl = tok_tables
    tl = np.asarray(tl)
    assert tl[tok.pad_id] == 0 and tl[tok.eos_id] == 0
    i = tok.pieces.index('{"')
    assert tl[i] == 2
    assert bytes(np.asarray(tb)[i, :2]) == b'{"'


def test_token_advance_matches_byte_advance(tok_tables):
    """Advancing coords by one multi-byte token == advancing the byte
    automaton over the token's bytes one at a time."""
    tok, tb, tl = tok_tables
    # Compact JSON only — the automaton deliberately has no whitespace
    # transitions (json_mask.py _WS).
    text = '{"name":[1,{"key":"v"},true],"x":-1e3}'
    ids = tok.encode(text, add_bos=False)
    assert tok.decode(ids) == text

    s_t = jnp.zeros((1,), jnp.int32)
    st_t = jnp.zeros((1,), jnp.int32)
    d_t = jnp.zeros((1,), jnp.int32)
    s_b, st_b, d_b = s_t, st_t, d_t
    for i in ids:
        s_t, st_t, d_t = json_advance_tokens(
            s_t, st_t, d_t, jnp.asarray([i]), tb, tl
        )
        for byte in tok.pieces[i].encode():
            s_b, st_b, d_b = json_advance(
                s_b, st_b, d_b, jnp.asarray([byte])
            )
        assert (int(s_t[0]), int(st_t[0]), int(d_t[0])) == (
            int(s_b[0]), int(st_b[0]), int(d_b[0])
        ), f"diverged after token {tok.pieces[i]!r}"


def test_mask_legal_tokens_only(tok_tables):
    """From the start state only document openers are legal; after '{\"'
    only key-continuation bytes are."""
    tok, tb, tl = tok_tables
    zero = jnp.zeros((1,), jnp.int32)
    mask = np.asarray(json_allowed_tokens(zero, zero, zero, tb, tl))[0]
    legal = {tok.pieces[i] for i in np.nonzero(mask)[0]}
    assert '{' in legal and '[' in legal and '{"' in legal
    assert 'true' not in legal and '0' not in legal and '}' not in legal
    # '": ...' merges are illegal at start; '\\n' (escape) too.
    assert '":' not in legal


def _roll_constrained(tok, tb, tl, seed, budget, temperature=1.0):
    """Sample a whole constrained generation with random logits."""
    state = SamplingState.create(1, seed=seed)
    state = update_slot(
        state, 0, temperature=temperature, top_k=0, top_p=1.0,
        seed=seed, eos_id=tok.eos_id, json_mode=True,
    )
    rng = np.random.default_rng(seed)
    out = []
    remaining = budget
    for _ in range(budget):
        logits = jnp.asarray(
            rng.standard_normal((1, tok.vocab_size)) * 4.0, jnp.float32
        )
        tokens, state = sample_core(
            logits, state,
            json_remaining=jnp.asarray([remaining], jnp.int32),
            json_token_tables=(tb, tl),
        )
        t = int(tokens[0])
        remaining -= 1
        if t == tok.eos_id:
            break
        out.append(t)
    return out


@pytest.mark.parametrize("seed", range(8))
def test_random_logits_always_parse(tok_tables, seed):
    tok, tb, tl = tok_tables
    ids = _roll_constrained(tok, tb, tl, seed=seed, budget=48)
    text = tok.decode(ids)
    doc = json.loads(text)  # raises on any grammar leak
    assert isinstance(doc, (dict, list))


@pytest.mark.parametrize("budget", [4, 6, 9, 14])
def test_tight_budget_still_closes(tok_tables, budget):
    """Budget feasibility must close the document before tokens run out —
    even when random logits would rather keep nesting."""
    tok, tb, tl = tok_tables
    for seed in range(4):
        ids = _roll_constrained(tok, tb, tl, seed=seed, budget=budget)
        text = tok.decode(ids)
        assert len(ids) <= budget
        json.loads(text)


def test_table_build_rejects_incomplete_vocab():
    """A vocab missing a closure byte (or exposing no byte info at all)
    must fail table construction — the engine then degrades to
    unconstrained sampling instead of masking everything out (review
    finding: all-False rows previously emitted pad-token garbage)."""

    class NoBrace(TinyBPE):
        def token_bytes(self, i):
            b = super().token_bytes(i)
            return None if b == b"}" else b

    with pytest.raises(ValueError, match="closure"):
        token_byte_table(NoBrace())

    class Opaque(Tokenizer):
        vocab_size = 16
        pad_id = bos_id = eos_id = 0

        def encode(self, text, add_bos=True):
            return []

        def decode(self, ids):
            return ""

    with pytest.raises(ValueError, match="closure"):
        token_byte_table(Opaque())


def test_infeasible_budget_degrades_to_eos(tok_tables):
    """remaining=1 makes every token budget-infeasible from S_START; the
    empty-mask fallback must end the generation with EOS, not spew pad
    tokens."""
    tok, tb, tl = tok_tables
    state = SamplingState.create(1)
    state = update_slot(
        state, 0, temperature=0.0, top_k=0, top_p=1.0, seed=0,
        eos_id=tok.eos_id, json_mode=True,
    )
    logits = jnp.zeros((1, tok.vocab_size), jnp.float32)
    tokens, _ = sample_core(
        logits, state, json_remaining=jnp.asarray([1], jnp.int32),
        json_token_tables=(tb, tl),
    )
    assert int(tokens[0]) == tok.eos_id


def test_hf_tokenizer_token_bytes_roundtrip(tmp_path):
    """HFTokenizer.token_bytes on a REAL fast tokenizer: train a tiny
    byte-level BPE locally (no network), then assert every encoded id's
    derived bytes concatenate back to the original text."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import decoders, models, pre_tokenizers, trainers

    from pilottai_tpu.engine.tokenizer import HFTokenizer

    raw = tokenizers.Tokenizer(models.BPE(unk_token=None))
    raw.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    raw.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        special_tokens=["<pad>", "<bos>", "<eos>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        '{"name": "value", "items": [1, 2.5, true, false, null], '
        '"nested": {"key": "abc"}}'
    ] * 50
    raw.train_from_iterator(corpus, trainer)
    raw.save(str(tmp_path / "tokenizer.json"))
    (tmp_path / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "pad_token": "<pad>", "bos_token": "<bos>", "eos_token": "<eos>",
    }))

    tok = HFTokenizer(tmp_path)
    tb, tl = token_byte_table(tok)
    assert int((tl > 0).sum()) > 100  # merges + byte alphabet usable
    for text in ('{"key": true}', '{"a": [1, 2.5], "b": null}'):
        ids = tok.encode(text, add_bos=False)
        recon = b"".join(
            bytes(tb[i, : tl[i]]) for i in ids if tl[i] > 0
        )
        assert recon == text.encode(), (text, recon)


@pytest.mark.asyncio
async def test_engine_json_mode_with_subword_tokenizer():
    """End-to-end: the native engine serves grammar-constrained JSON with
    a SUBWORD tokenizer — the path native.py:216 used to silently drop."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.native import NativeEngine
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    engine = NativeEngine(
        LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=128, engine_chunk=4, dtype="float32",
        ),
        platform="cpu",
    )
    engine.tokenizer = TinyBPE()  # swap in the subword vocab pre-start
    await engine.start()
    try:
        assert engine._json_tables is not None, "table build skipped"
        for seed in range(3):
            resp = await engine.generate(
                [ChatMessage(role="user", content="emit some json")],
                params=GenerationParams(
                    max_new_tokens=60, temperature=1.0, seed=seed,
                    json_mode=True,
                ),
            )
            doc = json.loads(resp.content)
            assert isinstance(doc, (dict, list))
    finally:
        await engine.stop()
