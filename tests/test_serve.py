"""Serve orchestrator end-to-end tests on the mock provider — the
"minimum end-to-end slice" (SURVEY §7.3, BASELINE config #1)."""

import asyncio

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.core.task import Task, TaskPriority, TaskStatus
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.serve import PriorityTaskQueue, Serve


def worker(backend=None, **cfg):
    handler = LLMHandler(
        LLMConfig(provider="mock"), backend=backend or MockBackend()
    )
    return BaseAgent(config=AgentConfig(role="processor", **cfg), llm=handler)


def make_serve(n_agents=1, manager_backend=None, config=None, **kwargs):
    agents = [worker() for _ in range(n_agents)]
    manager = LLMHandler(
        LLMConfig(provider="mock"), backend=manager_backend or MockBackend()
    )
    return Serve(
        name="test", agents=agents, manager_llm=manager,
        config=config or ServeConfig(max_concurrent_tasks=4, task_timeout=30),
        **kwargs,
    )


@pytest.mark.asyncio
async def test_quickstart_execute_task():
    """The README-style Quick Start path (reference §2.12-a intent)."""
    serve = make_serve()
    await serve.start()
    try:
        result = await serve.execute_task(
            {"type": "process_document", "description": "process the quarterly PDF"},
            timeout=30,
        )
        assert result.success
        assert serve.metrics["tasks_completed"] >= 1
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_dynamic_add_agent_and_string_task():
    serve = Serve(
        name="dyn",
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
    )
    serve.add_agent(worker())
    await serve.start()
    try:
        result = await serve.execute_task("just summarize this text", timeout=30)
        assert result.success
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_decomposition_pipeline_with_dependencies():
    """Manager decomposes into extract→analyze→summarize with deps; parent
    aggregates child outputs (reference stack §3.2 + config #3 shape)."""

    def manager_responder(prompt):
        if '"requires_decomposition"' in prompt:
            return {"requires_decomposition": True, "complexity": 7,
                    "estimated_resources": {"agents": 3, "llm_calls": 9},
                    "reasoning": "multi-stage"}
        return None

    serve = make_serve(
        n_agents=2, manager_backend=MockBackend(responders=[manager_responder])
    )
    await serve.start()
    try:
        result = await serve.execute_task(
            {"type": "complex_workflow", "description": "process the document"},
            timeout=60,
        )
        assert result.success
        assert isinstance(result.output, list) and len(result.output) == 3
        assert serve.metrics["subtasks_created"] == 3
        # Subtask chain respected dependencies: all completed.
        subtask_ids = result.metadata["subtask_ids"]
        statuses = [serve.get_task(s).status for s in subtask_ids]
        assert all(s == TaskStatus.COMPLETED for s in statuses)
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_failed_dependency_cascades():
    def manager_responder(prompt):
        if '"requires_decomposition"' in prompt:
            return {"requires_decomposition": True, "complexity": 5,
                    "estimated_resources": {}, "reasoning": ""}
        if '"subtasks"' in prompt:
            return {"subtasks": [
                {"description": "poison step", "type": "extract",
                 "priority": "normal", "depends_on": []},
                {"description": "dependent step", "type": "analyze",
                 "priority": "normal", "depends_on": [0]},
            ]}
        return None

    # Worker fails on the poison step (after agent-internal evaluation).
    def worker_responder(prompt):
        if '"task_complete"' in prompt and "poison step" in prompt:
            return {"task_complete": True, "action": "respond", "arguments": {},
                    "output": "bad output", "reasoning": ""}
        if '"success"' in prompt and "poison step" in prompt:
            return {"success": False, "quality": 0.1,
                    "issues": ["garbage output"], "suggestions": []}
        return None

    agents = [worker(backend=MockBackend(responders=[worker_responder]))]
    manager = LLMHandler(
        LLMConfig(provider="mock"),
        backend=MockBackend(responders=[manager_responder]),
    )
    serve = Serve(
        name="cascade", agents=agents, manager_llm=manager,
        config=ServeConfig(max_concurrent_tasks=2, task_timeout=30,
                           max_retry_attempts=0),
    )
    await serve.start()
    try:
        result = await serve.execute_task(
            {"type": "flow", "description": "doomed workflow"}, timeout=60
        )
        assert not result.success
        assert "subtasks failed" in result.error
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_retry_on_requires_retry():
    eval_count = {"n": 0}

    def manager_responder(prompt):
        if '"requires_retry"' in prompt:
            eval_count["n"] += 1
            return {"quality": 0.3 if eval_count["n"] == 1 else 0.9,
                    "requires_retry": eval_count["n"] == 1, "feedback": "redo"}
        return None

    serve = make_serve(
        manager_backend=MockBackend(responders=[manager_responder])
    )
    await serve.start()
    try:
        result = await serve.execute_task("retryable work", timeout=30)
        assert result.success
        assert serve.metrics["tasks_retried"] == 1
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_no_agents_fails_cleanly():
    serve = Serve(
        name="empty",
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(task_timeout=5),
    )
    await serve.start()
    try:
        result = await serve.execute_task("orphan work", timeout=20)
        assert not result.success
        assert "no available agent" in result.error
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_concurrent_tasks_throughput():
    serve = make_serve(n_agents=3)
    await serve.start()
    try:
        results = await serve.execute([f"task {i}" for i in range(10)])
        assert len(results) == 10 and all(r.success for r in results)
        metrics = serve.get_metrics()
        assert metrics["tasks_completed"] >= 10
        assert metrics["steps_per_sec"] > 0
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_cleanup_retention():
    serve = make_serve(
        config=ServeConfig(task_retention=0.01, max_concurrent_tasks=2,
                           task_timeout=30)
    )
    await serve.start()
    try:
        await serve.execute_task("ephemeral", timeout=30)
        await asyncio.sleep(0.05)
        dropped = serve.cleanup_once()
        assert dropped >= 1
    finally:
        await serve.stop()


# ----------------------- priority queue unit tests ---------------------- #

@pytest.mark.asyncio
async def test_priority_queue_orders_numerically():
    q = PriorityTaskQueue(maxsize=10)
    low = Task(description="low", priority=TaskPriority.LOW)
    critical = Task(description="crit", priority=TaskPriority.CRITICAL)
    normal = Task(description="norm", priority=TaskPriority.NORMAL)
    for t in (low, critical, normal):
        await q.put(t)
    assert (await q.get()).id == critical.id
    assert (await q.get()).id == normal.id
    assert (await q.get()).id == low.id


@pytest.mark.asyncio
async def test_priority_queue_eviction():
    q = PriorityTaskQueue(maxsize=2)
    a = Task(description="a", priority=TaskPriority.LOW)
    b = Task(description="b", priority=TaskPriority.NORMAL)
    await q.put(a); await q.put(b)
    c = Task(description="c", priority=TaskPriority.CRITICAL)
    evicted = await q.put(c)
    assert evicted is a and a.status == TaskStatus.CANCELLED
    d = Task(description="d", priority=TaskPriority.LOW)
    with pytest.raises(asyncio.QueueFull):
        await q.put(d)


@pytest.mark.asyncio
async def test_delegation_routes_complex_task_to_child():
    """VERDICT r4 #4: ServeConfig.delegation_enabled attaches a
    TaskDelegator; a task over the manager's complexity limit lands on a
    child via evaluate_delegation, and the outcome is recorded."""
    manager = worker(role_type="manager", max_task_complexity=3)
    children = [worker(), worker()]
    for c in children:
        manager.add_child_agent(c)
    serve = Serve(
        name="deleg", agents=children, manager_agent=manager,
        manager_llm=LLMHandler(LLMConfig(provider="mock"),
                               backend=MockBackend()),
        config=ServeConfig(
            delegation_enabled=True, decomposition_enabled=False,
            evaluation_enabled=False, max_concurrent_tasks=4,
        ),
    )
    await serve.start()
    try:
        assert serve.delegator is not None
        task = Task(description="heavy multi-part job", complexity=8)
        result = await serve.execute_task(task, timeout=30)
        assert result.success
        # Landed on a child, not the manager, and was recorded.
        assert task.agent_id in {c.id for c in children}
        assert task.metadata["delegation"]["reason"] == "complexity over limit"
        assert serve.delegator.get_metrics()[task.agent_id]["delegations"] == 1
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_delegation_disabled_bypasses():
    manager = worker(role_type="manager", max_task_complexity=3)
    child = worker()
    manager.add_child_agent(child)
    serve = Serve(
        name="nodeleg", agents=[child], manager_agent=manager,
        manager_llm=LLMHandler(LLMConfig(provider="mock"),
                               backend=MockBackend()),
        config=ServeConfig(
            delegation_enabled=False, decomposition_enabled=False,
            evaluation_enabled=False, max_concurrent_tasks=4,
        ),
    )
    await serve.start()
    try:
        assert serve.delegator is None
        task = Task(description="simple job", complexity=8)
        result = await serve.execute_task(task, timeout=30)
        assert result.success
        assert "delegation" not in task.metadata
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_delegation_prefers_unloaded_child():
    """The acceptance gate skips overloaded children."""
    manager = worker(role_type="manager", max_task_complexity=1)
    free = worker()
    busy = worker(max_queue_size=10)
    for c in (busy, free):
        manager.add_child_agent(c)
    # Saturate `busy` past the acceptance threshold (0.8).
    for i in range(9):
        await busy.add_task(Task(description=f"fill {i}"))
    serve = Serve(
        name="deleg2", agents=[busy, free], manager_agent=manager,
        manager_llm=LLMHandler(LLMConfig(provider="mock"),
                               backend=MockBackend()),
        config=ServeConfig(
            delegation_enabled=True, decomposition_enabled=False,
            evaluation_enabled=False, max_concurrent_tasks=4,
        ),
    )
    await serve.start()
    try:
        task = Task(description="needs a free worker", complexity=5)
        result = await serve.execute_task(task, timeout=30)
        assert result.success
        assert task.agent_id == free.id
    finally:
        await serve.stop()
