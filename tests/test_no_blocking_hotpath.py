"""Blocking-call tripwire for the device-feed pipeline (PERF_NOTES r8).

The asynchronous device-feed work rests on one invariant: the
dispatch/fold hot path of ``engine/batcher.py`` never issues a blocking
device read. D2H copies start at dispatch time (``_HostCopy``) and
folds materialize the already-in-flight copy; a reintroduced
``jax.device_get`` / ``block_until_ready`` / ``np.asarray(<device
array>)`` would silently re-serialize host and device and the only
symptom would be a slow bench three rounds later. This test walks the
hot-path methods' ASTs and fails on any such call outside the explicit
allowlist — the invariant can't rot unnoticed.

Deliberately NOT in the hot set: ``warmup`` / ``_autotune_page_strip``
(one-shot, device idle by construction), ``stop`` (shutdown quiesce),
``_rebuild_device_state`` (error recovery). Those are the allowed
blocking sites.
"""

import ast
import inspect
import textwrap

import pilottai_tpu.engine.batcher as batcher_mod
from pilottai_tpu.engine.batcher import ContinuousBatcher, _HostCopy

# Every method that runs per dispatch or per fold at steady state, on
# the device thread, the admission-prep thread or the reader thread.
HOT_PATH = {
    # device thread
    "_run", "_admit", "_dispatch_admissions", "_dispatch_prefill",
    "_dispatch_chunk", "_advance_segment", "_requeue_prepared",
    "_expire_deadlines", "_schema_tables", "_maybe_register",
    "_maybe_export", "_pick_chunk_blocks", "_chunk_useful",
    "_apply_restores",
    # admission-prep thread
    "_prep_loop", "_select_groups", "_prepare_prefill", "_drain_pending",
    "_prefix_hit",
    # reader thread
    "_read_loop", "_process_chunk", "_drain_first_reads",
    "_fold_first_tokens", "_check_finished", "_fire_stream",
    "_fail_group", "_fail_occupied_slots", "_release_pages_locked",
}

# KV cache tier (engine/kvcache/, ISSUE 10): the spill path runs at
# eviction time on the device/prep threads and the restore path on the
# prep thread under the slot lock — a blocking device read in either
# would re-serialize host and device exactly like one in the batcher.
# The whole package is scanned; the only sanctioned waits are
# ``SpillCopy.wait`` (materializes a copy STARTED at spill time — the
# _HostCopy discipline) and the cross-replica transfer surface
# (ISSUE 11/19): ``export_session`` / ``_export_entries`` + its ``add``
# closure, and ``import_session`` landing wire-decoded host arrays —
# control-plane operations the cell runs in an executor, never on the
# device/prep/reader threads.
KV_ASARRAY_ALLOWED_FUNCS = {
    "wait", "export_session", "_export_entries", "add", "import_session",
}

# Attribute calls that block the calling thread on the device, in any
# spelling (``jax.device_get(x)`` and ``x.block_until_ready()`` are both
# Attribute calls).
BANNED_ATTRS = {"device_get", "block_until_ready"}

# ``np.asarray`` is legal ONLY on host-resident data. Allowlist by
# (function name, unparsed first argument): these are numpy arrays the
# fold already holds (produced by ``_HostCopy.wait``, the sanctioned
# wait on an async copy started at dispatch).
ASARRAY_ALLOWED = {
    ("_fold_first_tokens", "host"),
}


def _violations_in(tree: ast.AST, func_name: str):
    """Banned blocking calls inside one function's AST."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in BANNED_ATTRS:
                out.append((func_name, node.lineno, ast.unparse(fn)))
            elif fn.attr == "asarray" and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("np", "numpy"):
                arg = ast.unparse(node.args[0]) if node.args else ""
                if (func_name, arg) not in ASARRAY_ALLOWED:
                    out.append((
                        func_name, node.lineno, f"np.asarray({arg})"
                    ))
        elif isinstance(fn, ast.Name) and fn.id in BANNED_ATTRS:
            out.append((func_name, node.lineno, fn.id))
    return out


def _hot_path_functions():
    """(name, ast) for every hot-path method actually present — with a
    guard that the set tracks reality: a renamed/deleted hot function
    must update this test, not silently fall out of coverage."""
    found = {}
    src = inspect.getsource(batcher_mod)
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in HOT_PATH:
                found[node.name] = node
    missing = HOT_PATH - set(found)
    assert not missing, (
        f"hot-path functions missing from engine/batcher.py: {missing} — "
        "renamed or removed? Update HOT_PATH to keep the tripwire honest."
    )
    return found


def test_no_blocking_calls_on_dispatch_or_fold_path():
    violations = []
    for name, node in _hot_path_functions().items():
        violations.extend(_violations_in(node, name))
    assert not violations, (
        "blocking device reads reintroduced on the device-feed hot path "
        f"(use _HostCopy started at dispatch time instead): {violations}"
    )


def _kvcache_violations():
    """Banned blocking calls anywhere in the KV cache tier package —
    spill starts async D2H at eviction, restore stages async H2D on the
    prep thread; neither may ever block on the device. np.asarray is
    legal only inside ``wait`` (the sanctioned materialize of a copy
    already in flight) or on literal host data."""
    import pilottai_tpu.engine.kvcache.host_tier as host_mod
    import pilottai_tpu.engine.kvcache.index as index_mod
    import pilottai_tpu.engine.kvcache.radix as radix_mod

    out = []
    for mod in (host_mod, index_mod, radix_mod):
        tree = ast.parse(inspect.getsource(mod))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                fn = call.func
                if isinstance(fn, ast.Attribute) and fn.attr in BANNED_ATTRS:
                    out.append((mod.__name__, node.name, call.lineno,
                                ast.unparse(fn)))
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "asarray"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("np", "numpy")
                    and node.name not in KV_ASARRAY_ALLOWED_FUNCS
                    # Literal host data (list/tuple/constant) never
                    # blocks on a device transfer.
                    and not (call.args and isinstance(
                        call.args[0],
                        (ast.List, ast.Tuple, ast.Constant),
                    ))
                ):
                    out.append((mod.__name__, node.name, call.lineno,
                                f"np.asarray({ast.unparse(call.args[0])})"
                                if call.args else "np.asarray(...)"))
                elif isinstance(fn, ast.Name) and fn.id in BANNED_ATTRS:
                    out.append((mod.__name__, node.name, call.lineno, fn.id))
    return out


def test_no_blocking_calls_in_kvcache_tier():
    violations = _kvcache_violations()
    assert not violations, (
        "blocking device reads in the KV cache tier's spill/restore "
        f"path (use SpillCopy started at spill time instead): {violations}"
    )


def test_kvcache_spill_copy_is_the_sanctioned_wait():
    """SpillCopy must start its copies at construction (spill time) and
    expose only a wait() that materializes them — the structure the
    kvcache scan's allowlist assumes. The restore paths must route
    through it."""
    from pilottai_tpu.engine.kvcache.host_tier import SpillCopy
    from pilottai_tpu.engine.kvcache.index import KVCacheIndex

    src = inspect.getsource(SpillCopy)
    tree = ast.parse(textwrap.dedent(src))
    init_src = ""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            init_src = ast.unparse(node)
    assert "copy_to_host_async" in init_src, (
        "SpillCopy.__init__ no longer starts the async copy — restores "
        "would pay a full blocking round trip"
    )
    assert ".wait()" in inspect.getsource(KVCacheIndex.lookup_dense)
    assert ".wait()" in inspect.getsource(KVCacheIndex.lookup_paged)


def test_tripwire_detects_reintroduced_device_get():
    """The checker itself must trip on the exact regressions it guards
    against — otherwise a refactor could neuter it silently."""
    poisoned = textwrap.dedent("""
        def _process_chunk(self, item):
            fetched = jax.device_get([item.toks, item.valid])
            jax.block_until_ready(fetched)
            host = np.asarray(item.toks)
            return fetched
    """)
    node = ast.parse(poisoned).body[0]
    found = _violations_in(node, "_process_chunk")
    kinds = {v[2] for v in found}
    assert "jax.device_get" in kinds
    assert "jax.block_until_ready" in kinds
    assert "np.asarray(item.toks)" in kinds


def test_host_copy_is_the_sanctioned_wait():
    """_HostCopy must start its copies at construction (dispatch time)
    and expose only a wait() that materializes them — the structure the
    allowlist above assumes."""
    src = inspect.getsource(_HostCopy)
    tree = ast.parse(textwrap.dedent(src))
    init_src = ""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            init_src = ast.unparse(node)
    assert "copy_to_host_async" in init_src, (
        "_HostCopy.__init__ no longer starts the async copy — folds "
        "would pay a full blocking round trip again"
    )
    # The batcher's fold path must actually route through it.
    batcher_src = inspect.getsource(ContinuousBatcher._process_chunk)
    assert ".wait()" in batcher_src
