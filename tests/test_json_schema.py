"""Schema→byte-DFA compiler: acceptance/rejection, ordering/optional
semantics, budget costs, the bank, and device-side constrained decoding."""

import json

import numpy as np
import pytest

from pilottai_tpu.engine.json_schema import (
    ACC,
    START,
    SchemaBank,
    UnsupportedSchema,
    compile_schema,
)

PROTOCOL = {
    "type": "object",
    "properties": {
        "requires_decomposition": {"type": "boolean"},
        "complexity": {"type": "integer"},
        "reasoning": {"type": "string"},
    },
    "required": ["requires_decomposition", "complexity", "reasoning"],
}


def test_flat_object_accepts_exact_shape():
    dfa = compile_schema(PROTOCOL)
    good = '{"requires_decomposition":false,"complexity":3,"reasoning":"ok"}'
    assert dfa.matches(good)
    assert json.loads(good)  # sanity: the accepted text is real JSON


@pytest.mark.parametrize("bad", [
    '{"complexity":3,"requires_decomposition":false,"reasoning":"x"}',  # order
    '{"requires_decomposition":false,"complexity":3}',                  # missing
    '{"requires_decomposition":"no","complexity":3,"reasoning":"x"}',   # type
    '{"requires_decomposition":false,"complexity":3.5,"reasoning":"x"}',  # int
    '{"requires_decomposition":false,"complexity":3,"reasoning":"x"} ',  # trail
    '{"requires_decomposition": false,"complexity":3,"reasoning":"x"}',  # ws
    '{"extra":1}',
])
def test_flat_object_rejects(bad):
    assert not compile_schema(PROTOCOL).matches(bad)


def test_optional_properties_skippable_in_order():
    dfa = compile_schema({
        "type": "object",
        "properties": {
            "a": {"type": "integer"},
            "b": {"type": "string"},
            "c": {"type": "boolean"},
        },
        "required": ["c"],
    })
    assert dfa.matches('{"a":1,"b":"x","c":true}')
    assert dfa.matches('{"b":"x","c":true}')
    assert dfa.matches('{"c":false}')
    assert not dfa.matches('{"a":1}')            # required c missing
    assert not dfa.matches('{"c":true,"a":1}')   # out of order
    assert not dfa.matches("{}")


def test_all_optional_allows_empty_object():
    dfa = compile_schema({
        "type": "object",
        "properties": {"a": {"type": "integer"}},
    })
    assert dfa.matches("{}")
    assert dfa.matches('{"a":7}')


def test_arrays_enums_unions_nested():
    dfa = compile_schema({
        "type": "object",
        "properties": {
            "tags": {"type": "array",
                     "items": {"enum": ["alpha", "beta"]}},
            "score": {"type": ["number", "null"]},
            "child": {
                "type": "object",
                "properties": {"n": {"type": "integer"}},
                "required": ["n"],
            },
        },
        "required": ["tags", "score", "child"],
    })
    assert dfa.matches('{"tags":[],"score":1.5,"child":{"n":2}}')
    assert dfa.matches('{"tags":["alpha","beta"],"score":null,"child":{"n":-1}}')
    assert not dfa.matches('{"tags":["gamma"],"score":1,"child":{"n":2}}')
    assert not dfa.matches('{"tags":[],"score":"x","child":{"n":2}}')
    assert not dfa.matches('{"tags":[],"score":1,"child":{}}')


def test_shared_prefix_keys_and_enum_members():
    dfa = compile_schema({
        "type": "object",
        "properties": {
            "a": {"enum": ["ab", "abc"]},
            "ab": {"type": "integer"},
        },
        "required": ["a", "ab"],
    })
    assert dfa.matches('{"a":"ab","ab":1}')
    assert dfa.matches('{"a":"abc","ab":22}')
    assert not dfa.matches('{"a":"abd","ab":1}')


def test_numbers_full_grammar():
    dfa = compile_schema({
        "type": "object",
        "properties": {"x": {"type": "number"}},
        "required": ["x"],
    })
    for v in ("0", "-7", "3.25", "1e9", "-2.5E-3", "0.5", "0e3", "-0.1"):
        assert dfa.matches('{"x":%s}' % v), v
    for v in (".5", "1.", "--2", "1e", "+3", "01", "-012", "00"):
        assert not dfa.matches('{"x":%s}' % v), v


def test_const_and_root_enum():
    dfa = compile_schema({"enum": ["yes", "no"]})
    assert dfa.matches('"yes"') and dfa.matches('"no"')
    assert not dfa.matches('"maybe"')
    dfa = compile_schema({
        "type": "object",
        "properties": {"kind": {"const": "task"}},
        "required": ["kind"],
    })
    assert dfa.matches('{"kind":"task"}')
    assert not dfa.matches('{"kind":"other"}')


def test_unsupported_rejected():
    for schema in (
        {"type": "object", "properties": {"a": {"$ref": "#/defs/x"}},
         "required": ["a"]},
        {"type": "object", "properties": {"a": {"anyOf": [{"type": "integer"}]}},
         "required": ["a"]},
        {"type": "string"},  # root must be object/array/enum/const
        {"type": "object", "properties": {"a": {"enum": [1, 12]}},
         "required": ["a"]},  # prefix-ambiguous literals
    ):
        with pytest.raises(UnsupportedSchema):
            compile_schema(schema)


def test_mincost_budget_feasibility():
    dfa = compile_schema({
        "type": "object",
        "properties": {"ok": {"type": "boolean"}},
        "required": ["ok"],
    })
    # Shortest doc: {"ok":true} = 11 bytes.
    assert int(dfa.mincost[START]) == 11
    assert int(dfa.mincost[ACC]) == 0
    # Every state on the accepting path can finish.
    state = START
    for b in b'{"ok":':
        state = dfa.step(state, b)
    assert int(dfa.mincost[state]) == 5  # 'true}' remains


def test_schema_bank_register_reuse_full():
    bank = SchemaBank(max_schemas=2, max_states=256)
    s1 = {"type": "object", "properties": {"a": {"type": "integer"}},
          "required": ["a"]}
    s2 = {"type": "object", "properties": {"b": {"type": "string"}},
          "required": ["b"]}
    i1 = bank.register(s1)
    v1 = bank.version
    assert bank.register(s1) == i1  # cached, no version bump
    assert bank.version == v1
    i2 = bank.register(s2)
    assert i1 != i2 and len(bank) == 2 and bank.version > v1
    # Full bank REFUSES (no eviction — in-flight slots hold row ids).
    s3 = {"type": "object", "properties": {"c": {"type": "boolean"}},
          "required": ["c"]}
    with pytest.raises(UnsupportedSchema):
        bank.register(s3)
    allowed, nxt, cost = bank.tables()
    assert allowed.shape[0] == 2 and cost[i1, START] < 2**30


# ---------------------- engine integration (cpu) ----------------------- #

@pytest.fixture(scope="module")
def schema_backend():
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.native import NativeEngine

    backend = NativeEngine(
        LLMConfig(
            model_name="llama-tiny", provider="cpu",
            engine_slots=2, engine_max_seq=256, engine_chunk=4,
        ),
        platform="cpu",
    )
    yield backend
    import asyncio

    asyncio.run(backend.stop())


def _gen(backend, schema, max_new=96, prompt="produce the record"):
    import asyncio

    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    async def run():
        resp = await backend.generate(
            [ChatMessage(content=prompt)],
            params=GenerationParams(
                max_new_tokens=max_new, temperature=0.0, json_schema=schema
            ),
        )
        return resp.content

    return asyncio.run(run())


def test_engine_output_matches_schema(schema_backend):
    """A random-weight model constrained by a schema emits a document
    that parses AND validates against the schema — by construction."""
    out = _gen(schema_backend, PROTOCOL)
    data = json.loads(out)
    assert set(data) == set(PROTOCOL["properties"])
    assert isinstance(data["requires_decomposition"], bool)
    assert isinstance(data["complexity"], int)
    assert isinstance(data["reasoning"], str)


def test_engine_schema_enum_and_nested(schema_backend):
    schema = {
        "type": "object",
        "properties": {
            "verdict": {"enum": ["approve", "reject"]},
            "detail": {
                "type": "object",
                "properties": {"score": {"type": "integer"}},
                "required": ["score"],
            },
        },
        "required": ["verdict", "detail"],
    }
    data = json.loads(_gen(schema_backend, schema))
    assert data["verdict"] in ("approve", "reject")
    assert isinstance(data["detail"]["score"], int)


def test_engine_schema_tight_budget_still_closes(schema_backend):
    """Budget feasibility: even a tight max_new_tokens produces a
    complete (possibly minimal) valid document, never a truncated one."""
    schema = {
        "type": "object",
        "properties": {"note": {"type": "string"}},
        "required": ["note"],
    }
    out = _gen(schema_backend, schema, max_new=14)  # min doc: {"note":""}
    data = json.loads(out)
    assert set(data) == {"note"}


def test_engine_schema_with_speculation():
    """Schema masking composes with speculative verify-blocks."""
    import asyncio

    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.native import NativeEngine
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    backend = NativeEngine(
        LLMConfig(
            model_name="llama-tiny", provider="cpu",
            engine_slots=2, engine_max_seq=256, engine_chunk=4,
            engine_speculate=4,
        ),
        platform="cpu",
    )
    try:
        async def run():
            resp = await backend.generate(
                [ChatMessage(content="emit json")],
                params=GenerationParams(
                    max_new_tokens=64, temperature=0.0, json_schema=PROTOCOL
                ),
            )
            return resp.content

        data = json.loads(asyncio.run(run()))
        assert set(data) == set(PROTOCOL["properties"])
    finally:
        asyncio.run(backend.stop())


def test_engine_unsupported_schema_degrades_to_generic(schema_backend):
    """anyOf → generic JSON grammar: output is still valid JSON."""
    out = _gen(schema_backend, {
        "type": "object",
        "properties": {"a": {"anyOf": [{"type": "integer"}]}},
        "required": ["a"],
    }, max_new=48)
    json.loads(out)  # well-formed, just not shape-checked


def test_agent_protocol_schema_exact_on_native_engine():
    """The full orchestrator→agent loop on a RANDOM-WEIGHT native engine
    yields schema-exact protocol JSON: analysis and evaluation carry
    exactly the rules.yaml contract fields with the right types —
    impossible without schema constraint (prompts/schemas.py)."""
    import asyncio

    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.serve import Serve

    llm = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu",
        engine_slots=2, engine_max_seq=512, engine_chunk=8,
        sampling={"max_new_tokens": 160, "temperature": 0.0},
    ))
    agent = BaseAgent(
        config=AgentConfig(role="worker", specializations=["generic"],
                           max_iterations=1),
        llm=llm,
    )
    serve = Serve(
        name="schema-proto", agents=[agent], manager_llm=llm,
        config=ServeConfig(decomposition_enabled=False,
                           evaluation_enabled=True),
    )

    async def run():
        await serve.start()
        try:
            return await serve.execute_task(
                "inventory check for bay 9", timeout=600
            )
        finally:
            await serve.stop()

    result = asyncio.run(run())
    analysis = result.metadata.get("analysis") or {}
    assert set(analysis) == {
        "understanding", "approach", "estimated_steps", "risks"
    }
    assert isinstance(analysis["estimated_steps"], int)
    assert isinstance(analysis["risks"], list)
    evaluation = result.metadata.get("evaluation") or {}
    assert set(evaluation) == {"success", "quality", "issues", "suggestions"}
    assert isinstance(evaluation["success"], bool)
    assert isinstance(evaluation["quality"], (int, float))


def test_protocol_schemas_all_compile():
    """Every rules.yaml wire schema stays inside the compiled subset."""
    from pilottai_tpu.prompts.schemas import PROTOCOL_SCHEMAS

    for name, schema in PROTOCOL_SCHEMAS.items():
        dfa = compile_schema(schema)
        assert dfa.n_states < 768, name  # fits the default bank


def test_greedy_forced_bytes_reach_accept():
    """Greedy walk taking the unique allowed byte where forced (and the
    cheapest where not) terminates at ACC — no dead ends."""
    dfa = compile_schema(PROTOCOL)
    state, out = START, bytearray()
    for _ in range(300):
        if state == ACC:
            break
        allowed = np.flatnonzero(dfa.allowed[state])
        assert len(allowed) > 0
        nxt = dfa.next[state, allowed]
        costs = dfa.mincost[nxt]
        pick = int(allowed[int(np.argmin(costs))])
        out.append(pick)
        state = int(dfa.next[state, pick])
    assert state == ACC
    parsed = json.loads(out.decode())
    assert set(parsed) == set(PROTOCOL["properties"])
