"""AgentFactory and TaskRouter tests (reference test strategy: SURVEY §4 —
registry validation, creation timeout, cleanup idempotence; routing by
forced metric inputs)."""

import asyncio

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, RouterConfig
from pilottai_tpu.core.factory import AgentFactory
from pilottai_tpu.core.router import TaskRouter
from pilottai_tpu.core.task import Task, TaskPriority
from pilottai_tpu.engine.handler import LLMHandler


def mock_llm():
    return LLMHandler(LLMConfig(provider="mock"))


@pytest.fixture(autouse=True)
def clean_registry():
    saved = dict(AgentFactory._agent_types)
    yield
    AgentFactory._agent_types = saved
    asyncio.run(AgentFactory.cleanup_all_agents())


class SlowAgent(BaseAgent):
    async def start(self):
        await asyncio.sleep(60)


def test_register_validates_class():
    with pytest.raises(TypeError):
        AgentFactory.register_agent_type("bad", dict)  # type: ignore[arg-type]
    with pytest.raises(ValueError, match="already registered"):
        AgentFactory.register_agent_type("worker", BaseAgent)


@pytest.mark.asyncio
async def test_create_agent_with_default_config():
    agent = await AgentFactory.create_agent("worker", llm=mock_llm())
    assert agent.config.role == "worker"
    assert agent.id in AgentFactory.active_agents()


@pytest.mark.asyncio
async def test_create_unknown_type():
    with pytest.raises(KeyError, match="unknown agent type"):
        await AgentFactory.create_agent("nope", llm=mock_llm())


@pytest.mark.asyncio
async def test_creation_timeout():
    AgentFactory.register_agent_type("slow", SlowAgent)
    AgentFactory.creation_timeout = 0.1
    try:
        with pytest.raises(RuntimeError, match="failed to start"):
            await AgentFactory.create_agent("slow", llm=mock_llm())
    finally:
        AgentFactory.creation_timeout = 30.0


@pytest.mark.asyncio
async def test_cleanup_idempotent():
    agent = await AgentFactory.create_agent("worker", llm=mock_llm())
    assert await AgentFactory.cleanup_agent(agent.id) is True
    assert await AgentFactory.cleanup_agent(agent.id) is False
    assert await AgentFactory.cleanup_agent("nonexistent") is False


@pytest.mark.asyncio
async def test_managed_agent_context():
    async with AgentFactory.managed_agent("worker", llm=mock_llm()) as agent:
        assert agent.id in AgentFactory.active_agents()
    assert agent.id not in AgentFactory.active_agents()


# ------------------------------ router --------------------------------- #

@pytest.mark.asyncio
async def test_router_prefers_specialized_idle_agent():
    generic = BaseAgent(config=AgentConfig(role="g"), llm=mock_llm())
    expert = BaseAgent(
        config=AgentConfig(role="e", specializations=["extract"]), llm=mock_llm()
    )
    await generic.start(); await expert.start()
    router = TaskRouter(RouterConfig(load_check_interval=0.0))
    chosen = await router.route_task(Task(description="x", type="extract"),
                                     [generic, expert])
    assert chosen is expert


@pytest.mark.asyncio
async def test_router_skips_overloaded_agents():
    a = BaseAgent(config=AgentConfig(role="a", max_queue_size=2), llm=mock_llm())
    b = BaseAgent(config=AgentConfig(role="b"), llm=mock_llm())
    await a.start(); await b.start()
    await a.add_task(Task(description="q1"))
    await a.add_task(Task(description="q2"))  # a is now at 100% queue
    router = TaskRouter(RouterConfig(load_check_interval=0.0))
    chosen = await router.route_task(Task(description="x"), [a, b])
    assert chosen is b


@pytest.mark.asyncio
async def test_router_returns_none_when_no_agent():
    router = TaskRouter(RouterConfig(route_attempts=2, retry_backoff=0.01))
    assert await router.route_task(Task(description="x"), []) is None


def test_static_priority_heuristic():
    import time as _t
    urgent = Task(
        description="x", complexity=8,
        dependencies=["a", "b", "c"],
        deadline=_t.time() + 60,
    )
    assert TaskRouter.get_task_priority(urgent) == TaskPriority.CRITICAL
    plain = Task(description="x")
    assert TaskRouter.get_task_priority(plain) == TaskPriority.NORMAL
