"""Device-profile parsing: transport-independent timing from perfetto
traces (VERDICT r4 weak #2 — bench numbers must separate engine time from
tunnel weather)."""

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.utils.device_profile import (
    DeviceWindow,
    parse_trace_dir,
    profile_device_window,
)


def test_profile_window_measures_compute(tmp_path):
    @jax.jit
    def f(x):
        for _ in range(4):
            x = x @ x
        return x

    x = jnp.ones((256, 256))
    f(x).block_until_ready()  # compile outside the window

    def run():
        y = x
        for _ in range(8):
            y = f(y)
        y.block_until_ready()

    out = profile_device_window(run, trace_dir=str(tmp_path))
    assert out["device_busy_s"] > 0
    assert out["n_events"] > 0
    assert 0 < out["busy_frac"] <= 1.0
    assert out["window_wall_s"] >= out["device_busy_s"] * out["busy_frac"] * 0.1


def test_parse_empty_dir_returns_zeros(tmp_path):
    out = parse_trace_dir(str(tmp_path))
    assert out["device_busy_s"] == 0.0
    assert out["n_events"] == 0


def test_device_window_start_stop(tmp_path):
    win = DeviceWindow(trace_dir=str(tmp_path)).start()
    jnp.ones((64, 64)).sum().block_until_ready()
    out = win.stop()
    assert out["window_wall_s"] > 0
