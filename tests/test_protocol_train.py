"""Protocol-model training recipe (train/protocol.py): data generation
matches the runtime's exact prompt rendering, prompt-masked loss works,
and — when the committed checkpoint is present — a real agent completes a
real task through the CPU engine (VERDICT r4 #1: task success must be
demonstrated with the real engine in the loop)."""

import asyncio
import json

import numpy as np
import pytest

from pilottai_tpu.engine.tokenizer import ByteTokenizer
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.train.protocol import (
    DEFAULT_CHECKPOINT,
    SERVE_MAX_NEW,
    SERVE_MAX_SEQ,
    _Rand,
    encode_example,
    make_example,
    protocol_batches,
)

PMS = {
    "agent": PromptManager("agent"),
    "orchestrator": PromptManager("orchestrator"),
}


def test_examples_cover_protocol_and_are_valid_json():
    r = _Rand(7)
    seen_markers = set()
    for _ in range(200):
        prompt, target = make_example(r, PMS)
        data = json.loads(target)  # every target parses
        assert target == json.dumps(data, separators=(",", ":"))  # compact
        assert prompt.endswith("<|assistant|>\n")  # runtime framing
        for marker in (
            '"task_complete"', '"selected_tools"', '"understanding"',
            '"requires_decomposition"', '"agent_id"', '"strategy"',
            '"subtasks"', '"success"', '"requires_retry"',
        ):
            if marker in prompt:
                seen_markers.add(marker)
    assert len(seen_markers) >= 8  # the curriculum covers the protocol


def test_prompt_rendering_matches_engine_request():
    """The training prompt for a tooled call must equal what the byte
    engine encodes for the same messages+tools (shared
    render_generic_request — parity by construction, checked anyway)."""
    from pilottai_tpu.engine.base import render_generic_request
    from pilottai_tpu.engine.types import ChatMessage, ToolSpec

    msgs = [
        ChatMessage(role="system", content="You are worker."),
        ChatMessage(role="user", content="do the thing"),
    ]
    tools = [ToolSpec(name="extract_sections", description="extract")]
    rendered = render_generic_request(msgs, tools)
    assert "Available tools:" in rendered
    assert "- extract_sections: extract" in rendered
    assert rendered.endswith("<|assistant|>\n")
    # And the engine's request builder produces exactly these ids.
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.native import NativeEngine
    from pilottai_tpu.engine.types import GenerationParams

    eng = NativeEngine(
        LLMConfig(model_name="llama-tiny", provider="cpu",
                  engine_max_seq=512),
        platform="cpu",
    )
    req = eng._build_request(msgs, tools, GenerationParams(max_new_tokens=8))
    assert req.prompt_ids == ByteTokenizer().encode(rendered)


def test_encode_example_mirrors_batcher_truncation():
    tok = ByteTokenizer()
    prompt = "p" * 2000
    target = '{"ok":true}'
    row, start = encode_example(prompt, target, tok, seq_len=SERVE_MAX_SEQ)
    keep = SERVE_MAX_SEQ - 1 - SERVE_MAX_NEW
    # Long prompt left-truncated exactly like batcher.submit.
    assert start == min(keep, SERVE_MAX_SEQ - len(target) - 2)
    assert row[start:] == tok.encode(target, add_bos=False) + [tok.eos_id]
    assert len(row) <= SERVE_MAX_SEQ
    # Short prompt keeps its BOS.
    row2, start2 = encode_example("short", target, tok, seq_len=SERVE_MAX_SEQ)
    assert row2[0] == tok.bos_id
    assert start2 == len("short") + 1


def test_batches_shape_and_mask():
    b = next(protocol_batches(4, 512, seed=3))
    assert b["tokens"].shape == (4, 512)
    assert (b["valid"] > b["loss_start"]).all()  # target is non-empty
    assert (b["loss_start"] > 0).all()


def test_loss_start_masks_prompt():
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.train.trainer import next_token_loss

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
    tokens = jnp.asarray(rng.integers(0, 16, size=(2, 8)), jnp.int32)
    valid = jnp.asarray([8, 8], jnp.int32)
    full = next_token_loss(logits, tokens, valid)
    masked = next_token_loss(
        logits, tokens, valid, loss_start=jnp.asarray([4, 4], jnp.int32)
    )
    # Masked loss equals the mean over only the target positions.
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    ll = jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)[..., 0]
    expect = -(ll[:, 3:].mean())
    assert np.isclose(float(masked), float(expect), rtol=1e-5)
    assert not np.isclose(float(masked), float(full), rtol=1e-5)


def test_train_steps_reduce_protocol_loss():
    """A few steps on the micro model must move the loss (recipe wiring:
    data gen → prompt-masked loss → optimizer)."""
    import jax

    from pilottai_tpu.models.registry import get_model_config
    from pilottai_tpu.train.trainer import TrainConfig, Trainer

    cfg = get_model_config("protocol-xs")
    t = Trainer(cfg, TrainConfig(
        learning_rate=3e-3, warmup_steps=2, total_steps=12, remat=False,
    ))
    state = t.init(jax.random.key(0))
    batches = protocol_batches(4, 384, seed=11)
    losses = []
    for _ in range(12):
        state, m = t.step(state, next(batches))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses


def _ckpt_present() -> bool:
    from pilottai_tpu.train.protocol import has_checkpoint

    return has_checkpoint()


@pytest.mark.skipif(not _ckpt_present(), reason="no committed checkpoint")
def test_committed_checkpoint_completes_tasks_on_real_engine():
    """The round-5 claim, verified in CI: a BaseAgent running on the CPU
    engine with the committed protocol checkpoint COMPLETES a task —
    real decoded tokens decide task_complete and success."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import (
        AgentConfig,
        LLMConfig,
        SamplingConfig,
    )
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.engine.handler import LLMHandler

    async def main():
        handler = LLMHandler(LLMConfig(
            model_name="protocol-s", provider="cpu",
            checkpoint_path=str(DEFAULT_CHECKPOINT),
            engine_slots=2, engine_max_seq=SERVE_MAX_SEQ,
            engine_chunk=16, dtype="float32",
            sampling=SamplingConfig(
                temperature=0.0, max_new_tokens=SERVE_MAX_NEW
            ),
        ))
        agent = BaseAgent(
            config=AgentConfig(
                role="worker", specializations=["generic"],
                max_iterations=2,
            ),
            llm=handler,
        )
        try:
            await agent.start()
            return await agent.execute_task(
                Task(description="check inventory 42 and report the result")
            )
        finally:
            await handler.stop()

    result = asyncio.run(main())
    assert result.success, (result.error, result.metadata)
    assert result.output  # the model produced a real answer
    evaluation = result.metadata["evaluation"]
    assert evaluation.get("success") in (True, "true")
