"""Tests for config, prompts, short-term memory, metrics and tracing."""

import time

import pytest

from pilottai_tpu.core.config import (
    AgentConfig,
    LLMConfig,
    LogConfig,
    ServeConfig,
)
from pilottai_tpu.core.memory import Memory
from pilottai_tpu.prompts.manager import PromptError, PromptManager
from pilottai_tpu.utils.metrics import MetricsRegistry
from pilottai_tpu.utils.tracing import Tracer


# ---------------------------- config ---------------------------------- #

def test_agent_config_roundtrip(tmp_path):
    cfg = AgentConfig(role="researcher", goal="find things", max_iterations=7)
    path = tmp_path / "agent.json"
    cfg.save(path)
    loaded = AgentConfig.load(path)
    assert loaded.role == "researcher" and loaded.max_iterations == 7


def test_agent_config_save_backup(tmp_path):
    path = tmp_path / "agent.json"
    AgentConfig(role="a").save(path)
    AgentConfig(role="b").save(path)
    assert AgentConfig.load(path).role == "b"
    assert (tmp_path / "agent.json.bak").exists()


def test_log_level_validated():
    with pytest.raises(ValueError):
        LogConfig(level="chatty")
    assert LogConfig(level="debug").level == "DEBUG"


def test_llm_config_defaults():
    cfg = LLMConfig()
    assert cfg.provider == "mock"
    assert cfg.sampling.max_new_tokens >= 1


def test_serve_config_defaults():
    cfg = ServeConfig()
    assert cfg.max_concurrent_tasks == 5
    assert cfg.max_queue_size == 1000


# ---------------------------- prompts --------------------------------- #

def test_prompt_placeholders_and_format():
    pm = PromptManager("agent")
    out = pm.format_prompt("system.base", role="tester", goal="g", backstory="b")
    assert "tester" in out
    # JSON braces in templates must survive formatting
    analysis = pm.format_prompt("task_analysis", task="T")
    assert '"understanding"' in analysis and "{understanding}" not in analysis


def test_prompt_missing_param_raises():
    pm = PromptManager("agent")
    with pytest.raises(PromptError):
        pm.format_prompt("task_analysis")


def test_orchestrator_namespace():
    pm = PromptManager("orchestrator")
    out = pm.format_prompt("task_decomposition", task="big job")
    assert "subtasks" in out


def test_unknown_prompt_raises():
    pm = PromptManager("agent")
    with pytest.raises(PromptError):
        pm.format_prompt("nope")


# ---------------------------- memory ---------------------------------- #

@pytest.mark.asyncio
async def test_memory_store_retrieve_by_tag():
    mem = Memory(max_entries=10)
    await mem.store({"a": 1}, tags={"x"})
    await mem.store({"a": 2}, tags={"x", "y"})
    await mem.store({"a": 3}, tags={"y"})
    got = await mem.retrieve(tags={"x"})
    assert {e.data["a"] for e in got} == {1, 2}
    both = await mem.retrieve(tags={"x", "y"})
    assert [e.data["a"] for e in both] == [2]


@pytest.mark.asyncio
async def test_memory_eviction_keeps_indexes_consistent():
    # Reference bug: positional indices drift after deque eviction
    # (SURVEY §2.12-h). Stable ids must survive eviction.
    mem = Memory(max_entries=3)
    for i in range(6):
        await mem.store(i, tags={f"t{i % 2}"})
    assert len(mem) == 3
    got = await mem.retrieve(tags={"t1"})
    assert all(isinstance(e.data, int) and e.data >= 3 for e in got)


@pytest.mark.asyncio
async def test_memory_timerange():
    mem = Memory()
    now = time.time()
    await mem.store("old", timestamp=now - 100)
    await mem.store("new", timestamp=now)
    got = await mem.retrieve_by_timerange(now - 10, now + 10)
    assert [e.data for e in got] == ["new"]


@pytest.mark.asyncio
async def test_memory_cleanup():
    mem = Memory()
    await mem.store("stale", timestamp=time.time() - 1000)
    await mem.store("fresh")
    dropped = await mem.cleanup(max_age=500)
    assert dropped == 1 and len(mem) == 1


# ---------------------------- metrics / tracing ------------------------ #

def test_metrics_counters_and_percentiles():
    m = MetricsRegistry()
    for _ in range(10):
        m.inc("steps")
    for v in range(100):
        m.observe("latency", v / 100)
    snap = m.snapshot()
    assert snap["counters"]["steps"] == 10
    assert 0.4 < snap["histograms"]["latency"]["p50"] < 0.6


def test_tracer_span_tree():
    tr = Tracer()
    with tr.span("parent") as p:
        with tr.span("child") as c:
            assert c.parent_id == p.span_id
            assert c.trace_id == p.trace_id
    spans = tr.finished()
    assert {s.name for s in spans} == {"parent", "child"}
    assert all(s.duration is not None for s in spans)
