"""Device-time/MFU attribution tests: windowed gauge arithmetic on
synthetic events (explicit timestamps — no wall-clock sensitivity), and
the slow-marker reconciliation of the live estimate against the
profiler-derived view (`utils/device_profile.py`) on the real CPU
engine — the pin that keeps the cheap always-on `engine.mfu` from
silently drifting away from profiler truth."""

import asyncio
import time

import pytest

from pilottai_tpu.obs.attribution import (
    DeviceTimeAttributor,
    peak_flops_per_chip,
)
from pilottai_tpu.utils.metrics import MetricsRegistry


# ---------------------------------------------------------------------- #
# Window arithmetic (synthetic timestamps)
# ---------------------------------------------------------------------- #


def _attr(window_s=60.0, **cfg):
    reg = MetricsRegistry()
    attr = DeviceTimeAttributor(registry=reg, window_s=window_s)
    attr.configure(**{
        "flops_per_token": 1e9, "peak_flops": 1e12, "n_chips": 2,
        "mesh_axes": ("model",), **cfg,
    })
    return attr, reg


def test_window_mfu_busy_and_collective_arithmetic():
    """engine.mfu = window FLOPs / (elapsed × peak × n_chips); busy is
    the complement of measured idle; collective_frac is the collective
    share of attributed time, per mesh axis too."""
    attr, reg = _attr()
    t = 1000.0
    attr.record("prefill", 0.5, tokens=100, at=t)       # window t0=999.5
    attr.record("decode", 1.0, tokens=400, at=t + 1.0)
    attr.record("collective", 0.5, flops=0.0, axis="model", at=t + 1.5)
    attr.record_gap(0.5, at=t + 2.0)
    g = reg.snapshot()["gauges"]
    flops = (100 + 400) * 1e9        # collective contributed 0 FLOPs
    elapsed = 2.5                    # 999.5 → 1002.0
    assert g["engine.mfu"] == pytest.approx(flops / (elapsed * 1e12 * 2))
    assert g["engine.device_busy_frac"] == pytest.approx(1 - 0.5 / elapsed)
    assert g["engine.collective_frac"] == pytest.approx(0.5 / 2.0)
    assert g["engine.collective_frac.model"] == pytest.approx(0.5 / 2.0)
    # Cumulative counters for delta-based consumers (bench sections).
    assert reg.get("engine.achieved_flops") == pytest.approx(flops)
    assert reg.get("engine.prefill_tokens") == 100
    assert reg.get("engine.attributed_decode_s") == pytest.approx(1.0)
    assert reg.get("engine.attributed_collective_s") == pytest.approx(0.5)
    assert reg.get("engine.idle_gap_s") == pytest.approx(0.5)


def test_window_prunes_old_events_counters_survive():
    """Gauges reflect the rolling window only; counters are cumulative."""
    attr, reg = _attr(window_s=10.0)
    attr.record("decode", 1.0, tokens=1000, at=100.0)
    attr.record("decode", 1.0, tokens=10, at=200.0)   # first event pruned
    g = reg.snapshot()["gauges"]
    # Window holds only the second event; elapsed capped at window_s.
    assert g["engine.mfu"] == pytest.approx(10 * 1e9 / (10.0 * 1e12 * 2))
    assert reg.get("engine.achieved_flops") == pytest.approx(1010 * 1e9)


def test_explicit_flops_override_and_phase_validation():
    attr, reg = _attr()
    attr.record("sampling", 0.1, tokens=50, flops=7e6, at=10.0)
    assert reg.get("engine.achieved_flops") == pytest.approx(7e6)
    with pytest.raises(ValueError):
        attr.record("warp", 0.1)
    # Negative/zero gaps are ignored, not booked.
    attr.record_gap(0.0, at=11.0)
    assert reg.get("engine.idle_gap_s") == 0.0


def test_snapshot_phase_shares_and_reset_window():
    # snapshot() prunes against the REAL clock — synthetic timestamps
    # must sit inside the rolling window relative to perf_counter.
    attr, _ = _attr()
    t = time.perf_counter()
    attr.record("prefill", 1.0, tokens=10, at=t - 4.0)
    attr.record("decode", 3.0, tokens=30, at=t - 1.0)
    snap = attr.snapshot()
    assert snap["phases"]["prefill"]["share"] == pytest.approx(0.25)
    assert snap["phases"]["decode"]["share"] == pytest.approx(0.75)
    assert snap["n_chips"] == 2 and snap["mesh_axes"] == ["model"]
    attr.reset_window()
    assert attr.snapshot()["attributed_s"] == 0.0


def test_peak_flops_platform_table_and_env_override(monkeypatch):
    assert peak_flops_per_chip("tpu") == pytest.approx(197e12)
    assert peak_flops_per_chip("unknown") == peak_flops_per_chip("cpu")
    monkeypatch.setenv("PILOTTAI_PEAK_FLOPS", "4.5e14")
    assert peak_flops_per_chip("tpu") == pytest.approx(4.5e14)
    monkeypatch.setenv("PILOTTAI_PEAK_FLOPS", "not-a-float")
    assert peak_flops_per_chip("tpu") == pytest.approx(197e12)


def test_flops_per_token_dense_and_moe():
    """The canonical formula: 2 FLOPs per ACTIVE parameter — dense uses
    every parameter, MoE only router + top-k experts."""
    from pilottai_tpu.models.registry import get_model_config

    dense = get_model_config("llama-tiny")
    assert dense.flops_per_token() == pytest.approx(2.0 * dense.param_count())
    moe = get_model_config("moe-tiny")
    assert moe.active_param_count() < moe.param_count()
    assert moe.flops_per_token() == pytest.approx(
        2.0 * moe.active_param_count()
    )
    # Dense ⊂ MoE consistency: zero inactive experts degrades to dense.
    all_active = moe.replace(n_active_experts=moe.n_experts)
    assert all_active.active_param_count() == all_active.param_count()


# ---------------------------------------------------------------------- #
# Slow: live estimate vs profiler on the real CPU engine
# ---------------------------------------------------------------------- #


@pytest.mark.slow
def test_live_mfu_reconciles_with_profiler_window():
    """The acceptance pin for bench `device_consistency.mfu_ok`: over one
    steady-state window measured BOTH ways — attribution counters (the
    live estimate) and a `utils/device_profile.DeviceWindow` trace (the
    profiler) — the two MFU figures must agree within 15%, the token
    accounting must be exact, and an idle-then-burst pattern must land
    its drain span in measured idle gaps, not in attributed decode time.

    CPU caveat: the profiler's host-lane fallback makes absolute
    `device_busy_s` untrustworthy on this backend (lane unions can span
    buffered events outside the window), so the profiler-derived MFU
    uses the profiler window's wall (`window_wall_s`) — the same pair
    bench's `mfu_live_vs_profiled_rel_err` compares — and `device_busy_s`
    is only asserted present/positive."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.obs import global_attribution
    from pilottai_tpu.utils.device_profile import DeviceWindow
    from pilottai_tpu.utils.metrics import global_metrics as gm

    peak = peak_flops_per_chip("cpu")

    def counters():
        return {
            "prefill_tokens": gm.get("engine.prefill_tokens"),
            "accepted": gm.get("engine.generated_tokens_device"),
            "flops": gm.get("engine.achieved_flops"),
            "decode_s": gm.get("engine.attributed_decode_s"),
            "prefill_s": gm.get("engine.attributed_prefill_s"),
            "idle_s": gm.get("engine.idle_gap_s"),
        }

    async def main():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=8,
            engine_chunk=8, engine_speculate=0, dtype="float32",
        ))

        async def wave(tag):
            await asyncio.gather(*[
                h.apredict(
                    f"attribution reconciliation {tag} req {i}",
                    params=GenerationParams(max_new_tokens=16,
                                            temperature=0.0),
                ) for i in range(8)
            ])

        await wave("settle")  # compiles + EMA settle, excluded

        # --- idle-then-burst: drain 1.5 s, then one wave ---------------
        c0 = counters()
        t_idle0 = time.perf_counter()
        await asyncio.sleep(1.5)
        await wave("burst")
        burst_wall = time.perf_counter() - t_idle0
        c1 = counters()
        d_burst = {k: c1[k] - c0[k] for k in c0}

        # --- steady traced window -------------------------------------
        await wave("resettle")
        c2 = counters()
        win = DeviceWindow().start()
        t0 = time.perf_counter()
        for k in range(3):
            await wave(f"traced{k}")
        wall = time.perf_counter() - t0
        prof = win.stop()
        c3 = counters()
        await h.stop()
        d_win = {k: c3[k] - c2[k] for k in c2}
        return d_burst, burst_wall, d_win, wall, prof

    d_burst, burst_wall, d_win, wall, prof = asyncio.run(main())

    # Idle-then-burst: the 1.5 s drain is measured idle, not decode.
    assert d_burst["idle_s"] >= 1.0, d_burst
    assert d_burst["decode_s"] + d_burst["prefill_s"] <= burst_wall, d_burst

    # Token accounting is exact: achieved FLOPs == (prefill + accepted)
    # × the formula the engine was CONFIGURED with (the engine's actual
    # ModelConfig — the byte tokenizer resizes vocab, so the registry's
    # stock config would be ~5% off).
    fpt = global_attribution.snapshot()["flops_per_token"]
    assert fpt > 0
    assert d_win["accepted"] > 0 and d_win["prefill_tokens"] > 0
    assert d_win["flops"] == pytest.approx(
        (d_win["prefill_tokens"] + d_win["accepted"]) * fpt, rel=1e-6,
    )

    # The profiler traced the window and saw execution.
    assert prof["device_busy_s"] > 0
    assert prof["window_wall_s"] > 0

    # THE reconciliation (bench's mfu_live_vs_profiled_rel_err): live
    # attribution MFU over the host-measured window vs the same FLOPs
    # over the profiler's window wall — within 15%.
    mfu_live = d_win["flops"] / (wall * peak)
    mfu_profiled = d_win["flops"] / (prof["window_wall_s"] * peak)
    rel_err = abs(mfu_profiled - mfu_live) / max(mfu_live, 1e-12)
    assert rel_err <= 0.15, (mfu_live, mfu_profiled, rel_err)

    # Attributed busy time stays inside the window it describes: a
    # saturated closed-loop wave attributes most of the wall, never
    # multiples of it (the pre-fix idle-accounting bug booked 17 s of
    # "decode" against a 0.5 s window).
    attributed = d_win["decode_s"] + d_win["prefill_s"]
    assert wall * 0.3 <= attributed <= wall * 1.25, (attributed, wall)
