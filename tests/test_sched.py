"""DAG-aware scheduler (pilottai_tpu/sched/ + the batcher's priority
backlog, ISSUE 12).

The contracts under test:

* **Byte identity** — greedy output is identical with the scheduler on
  (``sched_policy="dag"``: priority ordering, gang admission, aging)
  or off (``"fifo"``), across dense/paged × speculate on/off. The
  scheduler reorders WHEN requests admit, never what they compute.
* **Aging floor** — a LOW-priority request under sustained
  CRITICAL-priority load is delayed, not starved: it ages one rung per
  ``priority_aging_s`` and eventually outranks later-submitted
  critical work.
* **Gang admission** — sibling requests sharing a ``gang_id`` admit as
  a group when capacity suffices (``sched.gang_admits``), and fall
  back to partial admission after the bounded wait when it never can
  (``sched.gang_partial``) — they must not deadlock.
* **Pre-warm** — a predicted-prefix pre-warm restores spilled KV
  through the host tier before the real request arrives (prefix hit +
  byte-identical output), and is a pure no-op without the host tier
  (``engine_kvcache_host_mb=0``).
* **Visibility** — per-priority ``engine.backlog_wait_ms.*``
  histograms are fed at admission pop, so priority inversion is
  observable.
* **Criticality** — ``global_dag.criticality`` learns per-type stage
  profiles from finished dags and estimates remaining critical path
  for active ones; the scheduler turns a dominant estimate into a
  priority boost.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.obs.dag import DagLedger
from pilottai_tpu.sched import DagScheduler
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


def _make_batcher(sched_policy, *, paged=False, speculate=0, n_slots=4,
                  prefix_cache=0, host_mb=0, gang_wait_ms=40.0,
                  aging_s=2.0, prefix_min_len=None, max_seq=128,
                  chunk=4):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kwargs = dict(
        n_slots=n_slots, max_seq_len=max_seq, cache_dtype=jnp.float32,
        chunk_size=chunk, speculate=speculate, prefix_cache=prefix_cache,
        kvcache_host_mb=host_mb, use_pallas=False,
        sched_policy=sched_policy, gang_wait_ms=gang_wait_ms,
        priority_aging_s=aging_s, prefix_min_len=prefix_min_len,
    )
    if paged:
        kwargs.update(paged=True, page_size=16)
    return ContinuousBatcher(cfg, params, **kwargs)


# Mixed-priority workload with a complete gang (fits the slots), an
# over-sized gang (partial-admit fallback must fire) and ungoverned
# fillers. Distinct prompts so outputs are distinguishable.
def _sched_reqs():
    return [
        GenRequest(prompt_ids=list(range(3, 11)), max_new_tokens=5,
                   priority=0),
        GenRequest(prompt_ids=list(range(20, 30)), max_new_tokens=6,
                   priority=3),
        GenRequest(prompt_ids=list(range(31, 40)), max_new_tokens=4,
                   priority=2, gang_id="g1", gang_size=2),
        GenRequest(prompt_ids=list(range(41, 52)), max_new_tokens=4,
                   priority=2, gang_id="g1", gang_size=2),
        GenRequest(prompt_ids=list(range(55, 63)), max_new_tokens=3,
                   priority=1),
        GenRequest(prompt_ids=list(range(64, 75)), max_new_tokens=3,
                   priority=1, gang_id="g2", gang_size=9),
        GenRequest(prompt_ids=list(range(76, 85)), max_new_tokens=3,
                   priority=1, gang_id="g2", gang_size=9),
    ]


def _run(policy, *, paged, speculate):
    b = _make_batcher(policy, paged=paged, speculate=speculate)
    reqs = _sched_reqs()
    for r in reqs:
        b.submit(r)
    b.start()
    try:
        return [r.future.result(timeout=600) for r in reqs]
    finally:
        b.stop()


@pytest.mark.parametrize(
    "paged,speculate",
    [(False, 0), (False, 2), (True, 0), (True, 2)],
    ids=["dense", "dense-spec", "paged", "paged-spec"],
)
def test_scheduler_on_off_greedy_parity(paged, speculate):
    """The acceptance bar: priority ordering + gang admission + aging
    change nothing about any request's greedy output."""
    fifo = _run("fifo", paged=paged, speculate=speculate)
    admits0 = global_metrics.get("sched.gang_admits")
    partial0 = global_metrics.get("sched.gang_partial")
    dag = _run("dag", paged=paged, speculate=speculate)
    assert fifo == dag, (
        f"DAG scheduling changed greedy output (paged={paged}, "
        f"speculate={speculate})"
    )
    assert all(len(o) >= 1 for o in fifo)
    # Non-vacuous: the complete gang admitted as a group, and the
    # 9-member gang (only 2 present) fell back to partial admission
    # after the bounded wait instead of deadlocking.
    assert global_metrics.get("sched.gang_admits") > admits0
    assert global_metrics.get("sched.gang_partial") > partial0


def test_backlog_wait_histograms_fed():
    before = {
        name: (global_metrics.snapshot()["histograms"]
               .get(f"engine.backlog_wait_ms.{name}") or {}).get("count", 0)
        for name in ("low", "normal", "high", "critical")
    }
    _run("dag", paged=False, speculate=0)
    hists = global_metrics.snapshot()["histograms"]
    for name in ("low", "normal", "high", "critical"):
        h = hists.get(f"engine.backlog_wait_ms.{name}") or {}
        assert h.get("count", 0) > before[name], (
            f"engine.backlog_wait_ms.{name} never observed — priority "
            f"inversion would be invisible"
        )


def test_aging_floor_prevents_starvation():
    """Sustained critical-priority load may delay LOW work but must
    never starve it: with the aging floor at 0.05 s/rung, the LOW
    request outranks later-submitted CRITICAL traffic within ~0.15 s of
    backlog wait and completes ahead of the tail of the stream."""
    b = _make_batcher("dag", n_slots=1, aging_s=0.05, chunk=2)
    done_at = {}

    def _submit(name, prompt, priority, mnt=3):
        req = GenRequest(
            prompt_ids=prompt, max_new_tokens=mnt, priority=priority,
        )
        req.future.add_done_callback(
            lambda f, n=name: done_at.setdefault(n, time.perf_counter())
        )
        b.submit(req)
        return req

    blocker = _submit("blocker", list(range(3, 9)), 3, mnt=4)
    low = _submit("low", list(range(11, 18)), 0)
    b.start()
    crits = []
    try:
        # Keep critical work arriving for well past the aging horizon.
        t_end = time.time() + 1.5
        i = 0
        while time.time() < t_end:
            i += 1
            crits.append(_submit(
                f"crit-{i}", [20 + (i % 40), 21, 22, 23, 24], 3
            ))
            time.sleep(0.02)
        blocker.future.result(timeout=600)
        low.future.result(timeout=600)
        for c in crits:
            c.future.result(timeout=600)
    finally:
        b.stop()
    assert "low" in done_at
    last_crit = max(v for k, v in done_at.items() if k.startswith("crit"))
    assert done_at["low"] < last_crit, (
        "LOW-priority request finished after the entire critical "
        "stream — the aging floor failed to prevent starvation"
    )
    assert global_metrics.get("sched.priority_aged") > 0


# --------------------------------------------------------------------- #
# Speculative pre-warm
# --------------------------------------------------------------------- #

# ≥ 65 tokens apiece so the dense store's 64-token entry floor is
# cleared (entry = prompt minus last token); shared 70-token preamble.
_PRE = [(i % 90) + 5 for i in range(70)]
_WARM_SEQ = (
    (_PRE + [7, 9], 4),
    ([(i % 60) + 13 for i in range(70)], 4),   # evicts the first entry
    ([(i % 40) + 29 for i in range(70)], 4),   # keeps pressure on
    (_PRE + [7, 9, 11, 13], 4),                # the "next stage" arrival
)


def _run_prewarm(*, host_mb, prewarm, paged=False):
    b = _make_batcher(
        "dag", paged=paged, prefix_cache=1 if not paged else 4,
        host_mb=host_mb, n_slots=2, max_seq=256,
    )
    if paged and b.page_index is not None:
        b.page_index.capacity = 2  # force evictions through the tier
    b.start()
    try:
        outs = []
        for i, (prompt, mnt) in enumerate(_WARM_SEQ):
            if prewarm and i == len(_WARM_SEQ) - 1:
                # The scheduler predicts the next stage: pre-warm the
                # shared preamble, then wait for the prep thread to run
                # the lookup before the real request arrives.
                n0 = global_metrics.get("sched.prewarms")
                assert b.prewarm(list(_PRE)) is True
                deadline = time.time() + 30
                while (
                    global_metrics.get("sched.prewarms") == n0
                    and time.time() < deadline
                ):
                    time.sleep(0.01)
                assert global_metrics.get("sched.prewarms") > n0
            req = GenRequest(
                prompt_ids=list(prompt), max_new_tokens=mnt,
                session_id="warm-sess",
            )
            outs.append(b.submit(req).result(timeout=600))
        return outs
    finally:
        b.stop()


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_prewarm_restores_and_keeps_output_identical(paged):
    """A pre-warm of the predicted prefix restores the spilled KV ahead
    of the real request — which then hits device-resident KV — and the
    output is byte-identical to the un-pre-warmed run."""
    plain = _run_prewarm(host_mb=64, prewarm=False, paged=paged)
    hits0 = global_metrics.get("sched.prewarm_hits")
    restores0 = global_metrics.get("engine.kvcache.restores")
    warmed = _run_prewarm(host_mb=64, prewarm=True, paged=paged)
    assert warmed == plain, "pre-warm changed greedy output"
    assert global_metrics.get("sched.prewarm_hits") > hits0, (
        "pre-warm never found KV in either tier — the restore path "
        "was untested"
    )
    assert global_metrics.get("engine.kvcache.restores") > restores0


def test_prewarm_noop_parity_without_host_tier():
    """engine_kvcache_host_mb=0: pre-warm must be a harmless no-op —
    same outputs, no restores (there is no cold tier to restore from)."""
    plain = _run_prewarm(host_mb=0, prewarm=False)
    restores0 = global_metrics.get("engine.kvcache.restores")
    warmed = _run_prewarm(host_mb=0, prewarm=True)
    assert warmed == plain
    assert global_metrics.get("engine.kvcache.restores") == restores0


def test_prewarm_without_kvcache_is_rejected():
    b = _make_batcher("dag", prefix_cache=0)
    skipped0 = global_metrics.get("sched.prewarm_skipped")
    assert b.prewarm(list(range(100))) is False
    assert global_metrics.get("sched.prewarm_skipped") > skipped0


def test_min_len_floor_warns_once():
    """Prompts at or below the dense-store floor never cache (the PR 9
    NOTE); the engine must say so ONCE instead of missing silently.
    (Project loggers don't propagate to root, so count records with a
    directly attached handler rather than caplog.)"""
    import logging

    records = []

    class _Catcher(logging.Handler):
        def emit(self, record):
            if "prefix-store floor" in record.getMessage():
                records.append(record)

    b = _make_batcher("dag", prefix_cache=2, prefix_min_len=32,
                      n_slots=2, max_seq=128)
    assert b.prefix_store.min_len == 32
    assert b.kvcache.min_len == 32
    catcher = _Catcher()
    logger = getattr(b._log, "logger", b._log)  # unwrap LoggerAdapter
    logger.addHandler(catcher)
    b.start()
    try:
        for start in (5, 9):
            req = GenRequest(
                prompt_ids=list(range(start, start + 8)),
                max_new_tokens=2,
            )
            b.submit(req).result(timeout=600)
        b.prewarm(list(range(4)))
        deadline = time.time() + 30
        while not b._warned_min_len and time.time() < deadline:
            time.sleep(0.01)
    finally:
        b.stop()
        logger.removeHandler(catcher)
    assert len(records) == 1, (
        f"expected exactly one one-shot floor warning, got "
        f"{len(records)}"
    )


# --------------------------------------------------------------------- #
# Criticality estimator + scheduler boost
# --------------------------------------------------------------------- #

def _finish_synthetic(ledger, task_id, ttype, stages):
    """Record a finished task with top-level stages of given durations
    (synthetic perf_counter stamps)."""
    ledger.start(task_id, type=ttype)
    dag = ledger._active[task_id]
    t = dag.created
    for name, dur in stages:
        ledger.record(task_id, "stage", name, start=t, end=t + dur)
        t += dur
    dag.ended = t
    ledger.finish(task_id)


def test_criticality_learns_and_decays():
    ledger = DagLedger(registry=MetricsRegistry())
    # Two finished tasks teach the profile (EMA over both).
    _finish_synthetic(ledger, "a", "fanout",
                      [("analyze", 0.1), ("work", 0.4)])
    _finish_synthetic(ledger, "b", "fanout",
                      [("analyze", 0.1), ("work", 0.4)])
    assert ledger.criticality("nope") == 0.0
    # Fresh active task: both stages still ahead ≈ full profile.
    ledger.start("c", type="fanout")
    full = ledger.criticality("c")
    assert 0.4 < full <= 0.6
    # Analyze completed: remaining drops by roughly its EMA.
    now = time.perf_counter()
    ledger.record("c", "stage", "analyze", start=now - 0.1, end=now)
    after_analyze = ledger.criticality("c")
    assert after_analyze < full
    assert 0.3 < after_analyze <= 0.45
    # Work completed too: nothing left on the profile.
    ledger.record("c", "stage", "work", start=now, end=now + 0.4)
    assert ledger.criticality("c") < 0.05
    # Unknown type: estimator stays silent.
    ledger.start("d", type="mystery")
    assert ledger.criticality("d") == 0.0


def test_scheduler_boosts_dominant_critical_path():
    from pilottai_tpu.obs.dag import global_dag

    global_dag.reset()
    sched = DagScheduler(policy="dag")
    try:
        _finish_synthetic(global_dag, "t1", "fanout", [("work", 0.4)])
        _finish_synthetic(global_dag, "t2", "fanout", [("work", 0.4)])
        # Two live branches: "slow" has its whole profile ahead, "done"
        # finished its work stage — only the slow one is boosted.
        global_dag.start("slow", type="fanout")
        global_dag.start("done", type="fanout")
        now = time.perf_counter()
        global_dag.record("done", "stage", "work", start=now - 0.4, end=now)

        class T:
            def __init__(self, tid):
                self.id = tid
                self.priority = 1
                self.metadata = {}

        assert sched.priority_for(T("slow")) == 2
        assert sched.priority_for(T("done")) == 1
        # Policy off: static priority only, boost suppressed.
        sched.configure(policy="off")
        assert sched.priority_for(T("slow")) == 1
    finally:
        global_dag.reset()


def test_request_hints_thread_gang_and_learn_stages():
    sched = DagScheduler(policy="dag")
    calls = []
    sched.attach_prewarm("eng", lambda p, sid: calls.append((p, sid)))

    class T:
        def __init__(self, tid, meta):
            self.id = tid
            self.priority = 2
            self.metadata = meta

    meta = {"gang_id": "g-abc", "gang_size": 3}
    h = sched.request_hints(
        T("x", meta), "analyze", role="worker",
        prompt={"system": "SYS", "user": "analyze the thing"},
    )
    assert h["priority"] == 2
    assert h["gang_id"] == "g-abc" and h["gang_size"] == 3
    # Later stages of the same task do NOT gang (siblings drift apart).
    h2 = sched.request_hints(
        T("x", meta), "evaluate", role="worker",
        prompt={"system": "SYS", "user": "evaluate result one"},
    )
    assert "gang_id" not in h2
    # Two tasks traversing analyze → evaluate teach the transition and
    # converge the evaluate prefix to the shared head; the third task's
    # analyze then pre-warms it.
    sched.request_hints(T("y", {}), "analyze", role="worker",
                        prompt={"system": "SYS", "user": "analyze more"})
    sched.request_hints(T("y", {}), "evaluate", role="worker",
                        prompt={"system": "SYS", "user": "evaluate result two"})
    calls.clear()
    sched.request_hints(T("z", {}), "analyze", role="worker",
                        prompt={"system": "SYS", "user": "analyze again"})
    assert calls, "predicted next-stage pre-warm never fired"
    prefix, _sid = calls[0]
    assert prefix["system"] == "SYS"
    assert prefix["user"] == "evaluate result "  # converged common head
    # Policy off: hints reduce to static priority, no pre-warm.
    sched.configure(policy="off")
    calls.clear()
    h3 = sched.request_hints(T("w", meta), "analyze", role="worker",
                             prompt={"system": "SYS", "user": "u"})
    assert h3 == {"priority": 2}
    assert not calls


def test_priority_fill_dont_override_at_handler():
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    h = LLMHandler(LLMConfig(provider="mock"))
    _, _, p = h._normalize(
        ["hi"], None, None, None, priority=3, gang_id="g", gang_size=2,
    )
    assert p.priority == 3 and p.gang_id == "g" and p.gang_size == 2
    explicit = GenerationParams(priority=0)
    _, _, p2 = h._normalize(["hi"], None, explicit, None, priority=3)
    assert p2.priority == 0, "caller hint must not override explicit params"


def test_sched_series_export_complete():
    from pilottai_tpu.obs import export_completeness

    problems = [
        p for p in export_completeness()
        if "sched." in str(p) or "backlog_wait" in str(p)
    ]
    assert not problems, problems
