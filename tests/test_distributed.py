"""Multi-host distributed bring-up and preemption recovery, end to end.

VERDICT r1 #6: ``initialize_distributed`` had zero callers/tests and the
preemption story was narrative. Here:

* two REAL processes form a jax.distributed group over localhost (the
  DCN analogue), build one global mesh, and run a cross-process
  collective;
* a Serve process is SIGKILLed mid-run (the preemption model of
  BASELINE config #5) and a second process recovers its journal and
  completes the work;
* FaultTolerance replaces a dead agent and the queued work survives the
  transfer.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    ServeConfig,
)
from pilottai_tpu.core.factory import AgentFactory
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
from pilottai_tpu.serve import Serve

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_DIST_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh, initialize_distributed

    initialize_distributed(
        coordinator_address={coord!r}, num_processes=2, process_id={pid},
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4 and len(jax.local_devices()) == 2

    mesh = create_mesh(MeshConfig(data=4))
    sharding = NamedSharding(mesh, P("data"))
    data = np.arange(8, dtype=np.float32)
    x = jax.make_array_from_callback((8,), sharding, lambda idx: data[idx])
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    print("TOTAL", float(total), flush=True)
    """
)


def test_initialize_distributed_two_process_collective(tmp_path):
    """Two processes form one jax.distributed group and psum across it —
    the multi-host control path the engine/trainer use over DCN."""
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        script = tmp_path / f"child{pid}.py"
        script.write_text(
            _DIST_CHILD.format(repo=str(REPO), coord=coord, pid=pid)
        )
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    if any(
        "Multiprocess computations aren't implemented" in out for out in outs
    ):
        # Some jaxlib builds ship a CPU backend without cross-process
        # collectives at all; the bring-up itself (coordinator handshake,
        # 2-process device view) still ran — only the collective is
        # unavailable. Environment capability, not a code path to fix.
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert "TOTAL 28.0" in out, out


_CRASH_CHILD = textwrap.dedent(
    """
    import asyncio, json, sys
    sys.path.insert(0, {repo!r})
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.serve import Serve

    async def main():
        agent = BaseAgent(
            config=AgentConfig(role="processor"),
            llm=LLMHandler(
                LLMConfig(provider="mock"), backend=MockBackend(latency=30.0)
            ),
        )
        serve = Serve(
            name="victim", agents=[agent],
            manager_llm=LLMHandler(
                LLMConfig(provider="mock"), backend=MockBackend()
            ),
            config=ServeConfig(
                journal_path={journal!r}, decomposition_enabled=False,
            ),
        )
        await serve.start()
        ids = []
        for i in range(3):
            task = await serve.add_task(f"preemptible work item {{i}}")
            ids.append(task.id)
        print("SUBMITTED " + json.dumps(ids), flush=True)
        await asyncio.sleep(120)  # parent SIGKILLs long before this

    asyncio.run(main())
    """
)


@pytest.mark.asyncio
async def test_preemption_sigkill_then_recover(tmp_path):
    """The BASELINE config #5 story: a host dies mid-run (SIGKILL — no
    cleanup, like a TPU-VM preemption), a fresh process replays the
    journal, requeues the lost work, and completes it."""
    journal = str(tmp_path / "serve.jsonl")
    script = tmp_path / "victim.py"
    script.write_text(_CRASH_CHILD.format(repo=str(REPO), journal=journal))
    proc = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        # Read stdout on a helper thread: a wedged victim must fail the
        # test in 120s, not block readline forever.
        import queue as _q
        import threading

        lines: "_q.Queue[str]" = _q.Queue()
        threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],  # type: ignore[union-attr]
            daemon=True,
        ).start()
        ids = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                line = lines.get(timeout=1.0)
            except _q.Empty:
                continue
            if line.startswith("SUBMITTED "):
                ids = json.loads(line[len("SUBMITTED "):])
                break
        assert ids, "victim never submitted its tasks"
        time.sleep(0.3)  # let executions start (they run 30s mock steps)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # Survivor process: replay journal, requeue, complete with a healthy
    # (fast) agent pool.
    survivor = Serve(
        name="survivor",
        agents=[
            BaseAgent(
                config=AgentConfig(role="processor"),
                llm=LLMHandler(
                    LLMConfig(provider="mock"), backend=MockBackend()
                ),
            )
        ],
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(journal_path=journal, decomposition_enabled=False),
    )
    recovered = await survivor.recover()
    assert recovered == 3
    await survivor.start()
    try:
        results = await asyncio.gather(
            *[survivor.wait_for(tid, timeout=60) for tid in ids]
        )
        assert all(r.success for r in results)
    finally:
        await survivor.stop()


@pytest.mark.asyncio
async def test_fault_tolerance_replaces_dead_agent_with_queued_work():
    """A dead agent (stale heartbeat + ERROR status, recovery exhausted)
    is replaced and its queued tasks transfer to the replacement."""
    try:
        AgentFactory.register_agent_type("worker", BaseAgent)
    except ValueError:
        pass
    llm = LLMHandler(LLMConfig(provider="mock"), backend=MockBackend())
    agent = BaseAgent(config=AgentConfig(role="processor"), llm=llm)
    serve = Serve(
        name="ft", agents=[agent], manager_llm=llm,
        config=ServeConfig(decomposition_enabled=False),
    )
    await serve.start()
    ft = FaultTolerance(
        serve,
        config=FaultToleranceConfig(
            heartbeat_timeout=0.01, max_recovery_attempts=0,
        ),
    )
    try:
        from pilottai_tpu.core.task import Task

        queued = Task(description="survives the replacement")
        await agent.add_task(queued)
        # Simulate death: stale heartbeat + ERROR state.
        agent._last_heartbeat -= 3600
        agent.status = AgentStatus.ERROR
        await asyncio.sleep(0.02)

        statuses = await ft.check_once()
        assert statuses[agent.id].name == "CRITICAL"
        assert agent.id not in serve.agents, "dead agent still in the pool"
        assert len(serve.agents) == 1
        replacement = next(iter(serve.agents.values()))
        assert replacement.id != agent.id
        assert queued.id in {t.id for t in replacement.queued_tasks()}
        # The replacement is live: it executes work.
        result = await replacement.execute_task(Task(description="follow-up"))
        assert result.success
    finally:
        await serve.stop()
