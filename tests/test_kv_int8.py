"""int8 KV cache: quantized panels + per-token-per-head scales.

VERDICT r3 next-step 7: the dormant ``scales`` field is now populated.
Panels store int8; every read path (dense slices, paged gathers, the
Pallas paged kernel, prefix-store export, tail-prefill gathers)
dequantizes with the matching scales. Quality bound: symmetric per-token
int8 holds relative K/V error around 1/254 per element, so attention
outputs stay within ~1e-2 of the full-precision path.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import ChatMessage, GenerationParams
from pilottai_tpu.ops.kvcache import (
    KVCache,
    dequantize_kv,
    quantize_kv,
    write_chunk_rows,
    write_prompts,
)


def test_quantize_roundtrip_is_lossless_fixpoint():
    """dequantize → requantize must be exact (same scale recomputed) —
    the invariant that lets the prefix store traffic in full-precision
    panels over an int8-resident cache."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16), jnp.float32)
    q, s = quantize_kv(x)
    x2 = dequantize_kv(q, s, jnp.float32)
    q2, s2 = quantize_kv(x2)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_allclose(np.asarray(s), np.asarray(s2), rtol=1e-6)


def test_quantize_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 128), jnp.float32)
    q, s = quantize_kv(x)
    err = np.abs(np.asarray(dequantize_kv(q, s, jnp.float32)) - np.asarray(x))
    amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    assert (err <= amax / 127.0 * 0.51 + 1e-7).all()


def test_write_prompts_quantized_storage_accuracy():
    """Panels written through the quantizing path must dequantize back to
    the source values within the int8 bound."""
    L, A, T, K, H = 2, 2, 8, 2, 16
    ks = jax.random.normal(jax.random.PRNGKey(2), (L, A, T, K, H))
    vs = jax.random.normal(jax.random.PRNGKey(3), (L, A, T, K, H))
    lens = jnp.asarray([8, 5])
    cache = KVCache.create(L, 4, 16, K, H, dtype=jnp.float32, quantized=True)
    cache = write_prompts(cache, jnp.asarray([0, 2]), ks, vs, lens)
    assert cache.layers[0][0].dtype == jnp.int8
    got = np.asarray(dequantize_kv(
        cache.layers[1][0], cache.scales[1][0], jnp.float32
    ))
    want = np.asarray(ks[1]).swapaxes(1, 2)  # [A, K, T, H]
    np.testing.assert_allclose(got[0, :, :8], want[0, :, :8], atol=2e-2)
    np.testing.assert_allclose(got[2, :, :5], want[1, :, :5], atol=2e-2)
    # Ring write path too.
    rk = [jax.random.normal(jax.random.PRNGKey(4 + l), (4, K, 2, H))
          for l in range(L)]
    rv = [jax.random.normal(jax.random.PRNGKey(9 + l), (4, K, 2, H))
          for l in range(L)]
    cache = write_chunk_rows(
        cache, rk, rv, cache.lengths, jnp.asarray([2, 0, 2, 0])
    )
    got = np.asarray(dequantize_kv(
        cache.layers[0][0], cache.scales[0][0], jnp.float32
    ))
    np.testing.assert_allclose(got[0, :, 8:10], np.asarray(rk[0][0]),
                               atol=2e-2)


async def _gen(prompts, **cfg_kw):
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=4,
        engine_max_seq=256, engine_chunk=4, dtype="float32", **cfg_kw,
    ))
    await h.start()
    try:
        outs = []
        for p in prompts:
            r = await h.generate_response(
                [ChatMessage(content=p)],
                params=GenerationParams(max_new_tokens=12, temperature=0.0),
            )
            outs.append(r.content)
        return outs
    finally:
        await h.stop()


PRE = ("You are the orchestrator. Analyze the task and respond with "
       "strict JSON as instructed by the rules preamble. Task: ")


@pytest.mark.asyncio
@pytest.mark.parametrize("paged", [False, True])
async def test_engine_int8_kv_deterministic_and_composes(paged):
    """engine_kv_quantize='int8' serves deterministically (repeat ==
    repeat) with every fast path on: paged pool, speculation, prefix
    caching. Token-level parity with fp32 is NOT required (rounding may
    legitimately flip a greedy argmax on a random-weight model) — what
    is required is internal consistency."""
    prompts = [PRE + "alpha", PRE + "alpha", PRE + "beta"]
    outs = await _gen(
        prompts, engine_kv_quantize="int8", engine_paged_kv=paged,
        engine_page_size=16, engine_speculate=4, engine_prefix_cache=8,
    )
    assert outs[0] == outs[1], "int8 KV: exact repeat diverged"
    assert all(isinstance(o, str) for o in outs)


@pytest.mark.asyncio
async def test_engine_int8_kv_close_to_fp32():
    """The int8 engine's greedy stream should agree with fp32 for at
    least the first tokens of a short generation (the error bound is
    ~1e-2 on attention outputs; total drift over 12 byte-tokens on
    llama-tiny stays small)."""
    want = (await _gen([PRE + "gamma"]))[0]
    got = (await _gen([PRE + "gamma"], engine_kv_quantize="int8"))[0]
    agree = sum(a == b for a, b in zip(got[:6], want[:6]))
    assert agree >= 4, (want, got)
