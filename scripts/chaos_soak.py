#!/usr/bin/env python
"""Cross-subsystem chaos soak (ISSUE 16): a seeded, randomized fault
schedule driven through the *named injection registry* against a live
serving cell — two engine replicas on ``{'model':2,'data':2}`` survivor
ladders over the virtual 8-device CPU platform.

Each round runs a fixed greedy probe wave plus a session turn, injects
ONE fault drawn from the shuffled deck (shard loss, mid-decode step
fault, prefill fault, host-RAM rot at spill/restore, migration-frame
rot, prefill→decode handoff-frame rot, a stuck-dispatch latency
blip), and the soak then asserts the
system-wide invariants the fault domain promises:

* ``recovered_frac == 1.0`` — every non-shed request completed;
* **byte-identity** — every probe wave matches the clean reference
  wave byte for byte (recovery re-prefills; it never rewrites);
* **integrity** — every injected corruption is DETECTED (counted under
  ``engine.kvcache.integrity_failures``), never served (the final
  sweep resumes every soak session so each spilled entry crosses the
  restore verifier);
* **no stuck flights** — the cell drains to zero in-flight work;
* **export completeness** — a clean post-soak migration lands every
  entry (``accepted == entries``, nothing silently dropped).

Prints one JSON summary line and exits non-zero on any violation.
Wall clock is bounded by ``--budget-s`` (rounds stop early, the
invariant sweep always runs). The schedule is a pure function of
``--seed`` — rerunning a red CI seed locally reproduces the schedule.
"""

import argparse
import json
import os
import sys
import time


def _force_virtual_devices() -> None:
    """8 virtual CPU devices, set BEFORE jax's first import (device
    topology is fixed then — same trick as tests/conftest.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


_force_virtual_devices()
# Runnable as `python scripts/chaos_soak.py` from a source checkout.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import asyncio  # noqa: E402
import random  # noqa: E402

MESH = {"model": 2, "data": 2}
PROBES = [
    "chaos soak probe alpha: report fleet status",
    "the quick brown fox jumps over the lazy dog",
    "chaos soak probe gamma: shard the kv pool",
]
GREEDY = {"max_new_tokens": 12, "temperature": 0.0}


def _session_prompt(i: int) -> str:
    # Long enough to clear the host tier's entry floor on its own, and
    # divergent per session (distinct lineages).
    return (
        f"Session {i:03d} memory: persona agent-{i}; "
        f"goals g{i * 7}, g{i * 11}; constraints c{i * 13}. "
        + "analyze the quarterly report and respond with JSON please. " * 3
        + f"user: step {i}?"
    )


def _build_deck(rng: random.Random):
    """One entry per fault family; shuffled per-seed. ``max_shard``
    bounds permanent degradation so the ladders stay viable."""
    from pilottai_tpu.reliability.inject import global_injector as inj

    deck = [
        ("mesh.shard_loss", lambda: inj.arm(
            "mesh.shard_loss", value=rng.randrange(4), times=1, skip=1,
        )),
        ("engine.step", lambda: inj.arm(
            "engine.step", RuntimeError("chaos soak step fault"),
            times=1, skip=1,
        )),
        ("engine.prefill", lambda: inj.arm(
            "engine.prefill", RuntimeError("chaos soak prefill fault"),
            times=1,
        )),
        ("kvcache.spill.corrupt", lambda: inj.arm(
            "kvcache.spill.corrupt", value=True, times=1,
        )),
        ("kvcache.restore.corrupt", lambda: inj.arm(
            "kvcache.restore.corrupt", value=True, times=1,
        )),
        ("cell.migrate.corrupt", lambda: inj.arm(
            "cell.migrate.corrupt", value=True, times=1,
        )),
        ("cell.handoff.corrupt", lambda: inj.arm(
            "cell.handoff.corrupt", value=True, times=1,
        )),
        ("engine.dispatch.hang", lambda: inj.arm(
            "engine.dispatch.hang", delay=0.2, times=1,
        )),
    ]
    rng.shuffle(deck)
    return deck


async def soak(seed: int, rounds: int, budget_s: float):
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.distributed import ServingCell
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.reliability.inject import global_injector
    from pilottai_tpu.utils.metrics import global_metrics

    rng = random.Random(seed)
    t_start = time.monotonic()

    def cfg():
        return LLMConfig(
            model_name="llama-tiny", provider="cpu", dtype="float32",
            mesh_shape=dict(MESH),
            engine_slots=2, engine_max_seq=256, engine_chunk=8,
            engine_prefix_cache=1, engine_kvcache_host_mb=64,
        )

    # Disaggregated topology (ISSUE 19): cold long prompts route
    # through the prefill tier + KV handoff, so the handoff wire frame
    # is live in the soak and ``cell.handoff.corrupt`` has a real
    # payload to rot. Short probes go decode-direct; a corrupted or
    # unavailable handoff falls back colocated — every invariant below
    # must hold regardless of which path served a request.
    cell = ServingCell([LLMHandler(cfg()) for _ in range(2)],
                       cell_disagg="1p1d")
    await cell.start()
    global_injector.reset()
    params = GenerationParams(**GREEDY)
    results = []          # "ok" | "error" per request
    violations = []
    injections = []
    corrupt_fires = 0
    session_turns = {}    # sid -> (prompt, reply)

    async def probe_wave():
        got = await asyncio.gather(*[
            cell.apredict(p, params=params) for p in PROBES
        ], return_exceptions=True)
        for g in got:
            results.append("error" if isinstance(g, Exception) else "ok")
        return got

    async def session_turn(i):
        sid = f"cs-{i}"
        prompt = _session_prompt(i)
        try:
            reply = await cell.apredict(prompt, params=params,
                                        session_id=sid)
            session_turns[sid] = (prompt, reply)
            results.append("ok")
        except Exception:  # noqa: BLE001 — scored, not fatal
            results.append("error")

    fails0 = global_metrics.get("engine.kvcache.integrity_failures")
    losses0 = global_metrics.get("engine.shard_losses")
    handoffs0 = global_metrics.get("cell.handoffs")

    reference = await probe_wave()
    if any(isinstance(g, Exception) for g in reference):
        violations.append("clean reference wave errored")
    identical_waves = 0

    deck = _build_deck(rng)
    schedule = [deck[i % len(deck)] for i in range(rounds)]
    shard_events = 0
    done_rounds = 0
    for i, (name, arm) in enumerate(schedule):
        if time.monotonic() - t_start > budget_s * 0.8:
            break
        if name == "mesh.shard_loss":
            if shard_events >= 2:  # keep every ladder viable
                continue
            shard_events += 1
        arm()
        if name == "cell.handoff.corrupt":
            # A fresh cold long prompt forces a handoff attempt; the
            # rotted frame must be rejected by the integrity framing
            # (counted below) and the request served colocated anyway.
            prompt = (
                f"cold dossier {i}: "
                + f"shard {i} telemetry segment; " * 6
                + "summarize."
            )
            try:
                await cell.apredict(prompt, params=params)
                results.append("ok")
            except Exception:  # noqa: BLE001 — scored, not fatal
                results.append("error")
        if name == "cell.migrate.corrupt" and session_turns:
            sid = rng.choice(sorted(session_turns))
            try:
                report = await cell.migrate_session(sid)
                if report["accepted"] != 0 or (
                    report["entries"] and not report["rejected"]
                ):
                    violations.append(
                        f"round {i}: corrupt migration landed KV "
                        f"({report})"
                    )
            except Exception as exc:  # noqa: BLE001 — scored
                violations.append(f"round {i}: migrate raised {exc!r}")
        wave = await probe_wave()
        await session_turn(i)
        fired = global_injector.fired(name)
        injections.append({"round": i, "fault": name, "fired": fired})
        if name.endswith(".corrupt"):
            corrupt_fires += fired
        if all(
            not isinstance(g, Exception) and g == r
            for g, r in zip(wave, reference)
        ):
            identical_waves += 1
        else:
            violations.append(f"round {i} ({name}): probe wave diverged")
        global_injector.reset()
        done_rounds += 1

    # Invariant sweep 1: resume EVERY soak session so each spilled
    # entry crosses the restore verifier — a rotted one must be
    # detected (counted + dropped) and re-prefill byte-consistently.
    for sid, (prompt, reply) in sorted(session_turns.items()):
        try:
            await cell.apredict(
                prompt + reply + " user: and then?", params=params,
                session_id=sid,
            )
            results.append("ok")
        except Exception:  # noqa: BLE001 — scored
            results.append("error")

    # Invariant sweep 2: a clean migration must land every entry.
    export_complete = None
    if session_turns:
        sid = sorted(session_turns)[-1]
        try:
            report = await cell.migrate_session(sid)
            export_complete = (
                report["rejected"] == 0
                and report["accepted"] == report["entries"]
            )
            if not export_complete:
                violations.append(
                    f"post-soak migration incomplete: {report}"
                )
        except Exception as exc:  # noqa: BLE001 — scored
            export_complete = False
            violations.append(f"post-soak migration raised {exc!r}")

    # Invariant: the cell drains — no stuck flights anywhere.
    deadline = time.monotonic() + 60
    def inflight():
        return sum(r.inflight for r in cell.replicas.values())
    while inflight() and time.monotonic() < deadline:
        await asyncio.sleep(0.05)
    stuck = inflight()
    queued = sum(
        s.queue_depth for s in cell.signals()
    )
    if stuck or queued:
        violations.append(
            f"stuck flights after drain: inflight={stuck} queued={queued}"
        )

    detected = (
        global_metrics.get("engine.kvcache.integrity_failures") - fails0
    )
    if detected < corrupt_fires:
        violations.append(
            f"integrity: {corrupt_fires} corruption(s) injected, only "
            f"{detected} detected"
        )
    errors = results.count("error")
    recovered_frac = (
        round(results.count("ok") / len(results), 4) if results else 0.0
    )
    if recovered_frac < 1.0:
        violations.append(f"{errors} request(s) died (of {len(results)})")

    mesh_rungs = sorted(
        int(s.mesh_rung) for s in cell.signals()
    )
    await cell.stop()
    return {
        "seed": seed,
        "rounds": done_rounds,
        "rounds_requested": rounds,
        "requests": len(results),
        "recovered_frac": recovered_frac,
        "client_errors": errors,
        "identical_waves": identical_waves,
        "waves_injected": done_rounds,
        "byte_identity_ok": identical_waves == done_rounds,
        "shard_losses": int(
            global_metrics.get("engine.shard_losses") - losses0
        ),
        "mesh_rungs": mesh_rungs,
        "corruptions_injected": corrupt_fires,
        "corruptions_detected": int(detected),
        "handoffs": int(global_metrics.get("cell.handoffs") - handoffs0),
        "handoff_fallbacks": int(
            global_metrics.get("cell.handoff_fallbacks")
        ),
        "stuck_flights": int(stuck),
        "export_completeness": export_complete,
        "injections": injections,
        "wall_s": round(time.monotonic() - t_start, 1),
        "violations": violations,
        "ok": not violations,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=7)
    ap.add_argument("--budget-s", type=float, default=600.0,
                    help="wall-clock bound; rounds stop early past 80%%")
    args = ap.parse_args(argv)
    summary = asyncio.run(soak(args.seed, args.rounds, args.budget_s))
    print(json.dumps(summary))
    if not summary["ok"]:
        print("CHAOS SOAK VIOLATIONS:", file=sys.stderr)
        for v in summary["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
