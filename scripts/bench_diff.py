#!/usr/bin/env python3
"""Compare two bench rounds and flag per-section regressions.

The BENCH_r*.json trajectory is the repo's perf ledger, but "did round
N regress round N-1?" has so far been a by-hand diff over a growing
JSON. This tool makes it mechanical:

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py --repo-latest          # two newest in repo
    python scripts/bench_diff.py A.json B.json --threshold 0.15 \
        --fail-on-regression                            # CI gate mode

It walks the top level, every ``models.<section>`` block, every
``SLO.classes.<class>`` / ``CELL.classes.<class>`` block and the
``RECOVERY``, ``KVCACHE``, ``CELL``, ``SCHED`` (scheduler-on /
scheduler-off sub-blocks; straggler_frac and — in this section only —
critical_path_frac are down-good), ``MULTICHIP`` (per-chip steps/s,
MFU and per_chip_efficiency up-good; ``collective_frac*`` /
``collective_ms*`` down-good; the single-device reference under
``multichip.single``), ``QUANT`` (per-quant-mode sub-blocks:
steps/s and MFU up-good, ``weight_bytes*`` / the bytes-per-token
ratio down-good) and ``AUTOCONF`` (recommended / default knob-vector
sub-blocks with their per-class breakdowns, plus the forecast-on /
forecast-off burst sub-blocks: attainment and the measured forecast
lead up-good, peak burn down-good) and ``DISAGG`` (colocated /
disagg topology sub-blocks with their decode-only baseline and
mixed-workload phase sub-blocks: ttft/tpot percentiles, handoff_ms
and the interference ratios down-good; handoff_success and
attainment up-good) blocks, compares numeric
metrics whose direction it knows (steps/s, MFU, attainment, busy_frac,
recovered_frac, prefix_hit_rate, affinity_hit_rate,
prefill_tokens_saved up = good; p50/p99, host_gap, burn_rate,
recovery_ms, restore_ms, migration_ms, drain_s, shed, tokens_replayed,
overhead fractions down = good), and prints a readable table with
deltas, flagging moves beyond
``--threshold`` (default 10%). ``x/y`` success strings compare as ratios. Keys with no
known direction (config echoes, counts) are skipped.

Exit status: 0 unless ``--fail-on-regression`` is set AND at least one
regression beyond threshold was found. The CI job runs report-only —
committed rounds may trade one metric for another deliberately; the
table in the log is the review artifact.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

# Substring → direction. First match wins; order matters (e.g.
# "overhead_frac" must match before the generic "frac").
HIGHER_BETTER = (
    "steps_per_sec", "tokens_per_sec", "mfu", "attainment", "busy_frac",
    "chunk_utilization", "vs_baseline", "success", "hit_rate",
    "critical_path_frac", "completed",
    # RECOVERY section (ISSUE 9): fraction of fault-interrupted requests
    # that completed anyway.
    "recovered_frac", "outputs_identical", "fault_fired",
    # KVCACHE section (ISSUE 10): prefix_hit_rate matches "hit_rate"
    # above; prefill FLOPs the tier saved are the other up-good axis.
    "tokens_saved",
    # MULTICHIP section (ISSUE 13): sharded-vs-single-device scaling.
    "per_chip_efficiency", "total_speedup",
    # CHAOS section (ISSUE 16): invariant holds are up-good — probe
    # waves matching the clean reference, injected rot detected, the
    # post-soak migration landing every entry.
    "byte_identity", "identical_waves", "corruptions_detected",
    "export_completeness",
    # AUTOCONF section (ISSUE 18): seconds of capacity lead the arrival
    # forecast bought before the scripted burst (attainment_* headlines
    # already match "attainment" above).
    "forecast_lead",
)
LOWER_BETTER = (
    "overhead_frac", "straggler_frac", "p50", "p90", "p99", "host_gap",
    "burn_rate", "_ms", "latency", "shed", "errors", "missed", "drain_s",
    # RECOVERY section: recovery_ms_* already match "_ms"; replayed
    # tokens, failure-path rebuilds, strikes-exhausted failures and
    # fold-poison counts are all cost.
    "tokens_replayed", "rebuilds", "recovery_failed", "poisoned",
    "degrade_level", "watchdog_stalls",
    # MULTICHIP section: interconnect share of device time (matches
    # collective_frac, collective_frac_model/.data and — via "_ms" —
    # collective_ms_per_step; must precede any up-good "frac" rule).
    "collective",
    # QUANT section (ISSUE 14): the decode weight stream is the cost —
    # matches weight_bytes, weight_bytes_per_token and the
    # bytes_per_token_int4_vs_int8 / quant_bytes_per_token_ratio
    # headlines.
    "weight_bytes", "bytes_per_token",
    # CHAOS section (ISSUE 16): permanent capacity shed, undetected-rot
    # exposure and wedged work are all cost (client_errors matches
    # "errors" above; recovered_frac is already up-good).
    "shard_losses", "integrity_failures", "stuck_flights", "mesh_rungs",
    # AUTOCONF section (ISSUE 18): worst interactive burn seen during
    # the scripted burst simulation.
    "peak_burn",
    # DISAGG section (ISSUE 19): interference ratios (mixed-phase TPOT
    # over decode-only baseline — disaggregation exists to hold them
    # down), handoff fallbacks and integrity-rejected frames are cost;
    # handoff_success already matches "success", handoff_ms_* matches
    # "_ms", ttft/tpot percentiles match "p50"/"p99".
    "interference", "fallbacks", "rejected",
)


def _direction(key: str, section: str = "") -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = don't judge.

    Section-aware exception: in the SCHED section the critical-path
    FRACTION is the parent fan-out's makespan over total task time —
    the scheduler exists to drive it DOWN — whereas the swarm/pipeline
    sections' critical_path_frac is an attribution-tightness check
    (cp ≈ e2e, higher = better-covered)."""
    if section.startswith("sched") and "critical_path_frac" in key:
        return -1
    for sub in LOWER_BETTER:
        if sub in key:
            return -1
    for sub in HIGHER_BETTER:
        if sub in key:
            return +1
    return None


def _numeric(value: Any) -> Optional[float]:
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        m = re.fullmatch(r"(\d+)\s*/\s*(\d+)", value.strip())
        if m and int(m.group(2)):
            return int(m.group(1)) / int(m.group(2))
    return None


def _balanced(text: str, start: int) -> Optional[str]:
    """The balanced ``{...}`` substring beginning at ``start`` (which
    must index a ``{``), string-literal aware; None when unterminated."""
    depth = 0
    in_str = False
    escape = False
    for i in range(start, len(text)):
        ch = text[i]
        if in_str:
            if escape:
                escape = False
            elif ch == "\\":
                escape = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return text[start:i + 1]
    return None


_SCALAR_PAIR = re.compile(
    r'"([A-Za-z0-9_.@-]+)"\s*:\s*'
    r'(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|true|false|null|"[^"]*")'
)


def _from_tail(tail: str) -> Dict[str, Any]:
    """Recover a comparable document from a driver tail capture (the
    LAST ~2000 bytes of bench output — valid JSON only from some offset
    onward). Named blocks (``models``, ``SLO``) are extracted via
    balanced-brace matching and parsed properly; whatever scalar pairs
    remain outside them are treated as top-level metrics. Lossy by
    nature — metrics truncated off the head are simply absent, and the
    diff only compares keys present in BOTH rounds."""
    doc: Dict[str, Any] = {}
    remainder = tail
    for block in ("models", "SLO", "phases", "KVCACHE", "CELL", "SCHED",
                  "MULTICHIP", "QUANT", "CHAOS", "AUTOCONF", "DISAGG"):
        marker = f'"{block}": '
        at = remainder.find(marker)
        if at < 0:
            continue
        brace = remainder.find("{", at + len(marker) - 1)
        if brace < 0:
            continue
        body = _balanced(remainder, brace)
        if body is None:
            continue
        try:
            doc[block] = json.loads(body)
        except json.JSONDecodeError:
            continue
        remainder = remainder[:at] + remainder[brace + len(body):]
    for key, raw in _SCALAR_PAIR.findall(remainder):
        try:
            doc.setdefault(key, json.loads(raw))
        except json.JSONDecodeError:
            pass
    doc.pop("phases", None)  # percentile sub-dicts, not section metrics
    return doc


def _unwrap(doc: Dict[str, Any]) -> Dict[str, Any]:
    """The committed BENCH_r*.json files are driver capture records: the
    bench's own JSON lives under ``parsed`` when the driver parsed it,
    else only the trailing bytes survive under ``tail``. Accept the raw
    bench shape, the parsed wrapper, and the tail capture."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and (
        "metric" in parsed or "models" in parsed
    ):
        return parsed
    tail = doc.get("tail")
    if isinstance(tail, str) and ("models" in tail or "metric" in tail):
        return _from_tail(tail)
    return doc


def _sections(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """section name → flat {metric: value}."""
    doc = _unwrap(doc)
    out: Dict[str, Dict[str, Any]] = {"top": {}}
    for key, value in doc.items():
        if key in ("models", "SLO", "phases", "RECOVERY", "KVCACHE",
                   "CELL", "SCHED", "MULTICHIP", "QUANT", "CHAOS",
                   "AUTOCONF", "DISAGG"):
            continue
        num = _numeric(value)
        if num is not None:
            out["top"][key] = num
    recovery = doc.get("RECOVERY")
    if isinstance(recovery, dict):
        out["recovery"] = {
            k: n for k, v in recovery.items()
            if (n := _numeric(v)) is not None
        }
    kvcache = doc.get("KVCACHE")
    if isinstance(kvcache, dict):
        out["kvcache"] = {
            k: n for k, v in kvcache.items()
            if (n := _numeric(v)) is not None
        }
    cell = doc.get("CELL")
    if isinstance(cell, dict):
        # Scalars at the section root (affinity_hit_rate, migration_ms,
        # drain_s, migrations ...) plus per-class sub-blocks with
        # attainment / burn_rate / shed / routed, SLO-style.
        out["cell"] = {
            k: n for k, v in cell.items()
            if (n := _numeric(v)) is not None
        }
        for cls, block in (cell.get("classes") or {}).items():
            if isinstance(block, dict):
                out[f"cell.{cls}"] = {
                    k: n for k, v in block.items()
                    if (n := _numeric(v)) is not None
                }
    sched = doc.get("SCHED")
    if isinstance(sched, dict):
        # Section-root scalars plus the scheduler-on / scheduler-off
        # sub-blocks (straggler/critical-path fracs, steps/s, success).
        out["sched"] = {
            k: n for k, v in sched.items()
            if (n := _numeric(v)) is not None
        }
        for mode in ("on", "off"):
            block = sched.get(mode)
            if isinstance(block, dict):
                out[f"sched.{mode}"] = {
                    k: n for k, v in block.items()
                    if (n := _numeric(v)) is not None
                }
    multichip = doc.get("MULTICHIP")
    if isinstance(multichip, dict):
        # Section-root scalars (per-chip steps/s, MFU, per-axis
        # collective fracs, efficiency) plus the single-device reference
        # sub-block the sharded numbers are judged against.
        out["multichip"] = {
            k: n for k, v in multichip.items()
            if (n := _numeric(v)) is not None
        }
        single = multichip.get("single_chip")
        if isinstance(single, dict):
            out["multichip.single"] = {
                k: n for k, v in single.items()
                if (n := _numeric(v)) is not None
            }
    quant = doc.get("QUANT")
    if isinstance(quant, dict):
        # Section-root scalars (the bytes ratio, the quant group echo is
        # skipped by direction) plus one sub-block per quantization mode
        # with steps/s, MFU and the measured weight-stream bytes.
        out["quant"] = {
            k: n for k, v in quant.items()
            if (n := _numeric(v)) is not None
        }
        for mode, block in (quant.get("modes") or {}).items():
            if isinstance(block, dict):
                out[f"quant.{mode}"] = {
                    k: n for k, v in block.items()
                    if (n := _numeric(v)) is not None
                }
    chaos = doc.get("CHAOS")
    if isinstance(chaos, dict):
        # Invariant scalars (recovered_frac, identical_waves,
        # stuck_flights, corruptions detected vs injected, shard
        # losses); the per-round injection schedule is a list and
        # stays out of the numeric diff.
        out["chaos"] = {
            k: n for k, v in chaos.items()
            if (n := _numeric(v)) is not None
        }
    autoconf = doc.get("AUTOCONF")
    if isinstance(autoconf, dict):
        # Section-root scalars (the measured forecast lead), the
        # recommended / default knob-vector sub-blocks — each a measured
        # bench_slo run: steps/s + per-class attainment/p99s/burn — and
        # the forecast-on / forecast-off scripted-burst sub-blocks
        # (peak_burn, forecast_lead_s; the phase indices carry no
        # direction and stay out of the diff).
        out["autoconf"] = {
            k: n for k, v in autoconf.items()
            if (n := _numeric(v)) is not None
        }
        for mode in ("recommended", "default"):
            block = autoconf.get(mode)
            if not isinstance(block, dict):
                continue
            out[f"autoconf.{mode}"] = {
                k: n for k, v in block.items()
                if (n := _numeric(v)) is not None
            }
            for cls, cblock in (block.get("classes") or {}).items():
                if isinstance(cblock, dict):
                    out[f"autoconf.{mode}.{cls}"] = {
                        k: n for k, v in cblock.items()
                        if (n := _numeric(v)) is not None
                    }
        for mode in ("on", "off"):
            block = (autoconf.get("forecast") or {}).get(mode)
            if isinstance(block, dict):
                out[f"autoconf.forecast_{mode}"] = {
                    k: n for k, v in block.items()
                    if (n := _numeric(v)) is not None
                }
    disagg = doc.get("DISAGG")
    if isinstance(disagg, dict):
        # Section-root scalars (rates, host_cores carries no direction)
        # plus one sub-block per topology — each with its interference
        # ratios and handoff health — and each topology's decode-only
        # baseline / mixed-workload phase sub-blocks (ttft/tpot/e2e
        # percentiles + attainment, SLO-style).
        out["disagg"] = {
            k: n for k, v in disagg.items()
            if (n := _numeric(v)) is not None
        }
        for topo in ("colocated", "disagg"):
            block = disagg.get(topo)
            if not isinstance(block, dict):
                continue
            out[f"disagg.{topo}"] = {
                k: n for k, v in block.items()
                if (n := _numeric(v)) is not None
            }
            for phase in ("baseline", "mixed"):
                pblock = block.get(phase)
                if isinstance(pblock, dict):
                    out[f"disagg.{topo}.{phase}"] = {
                        k: n for k, v in pblock.items()
                        if (n := _numeric(v)) is not None
                    }
    for name, block in (doc.get("models") or {}).items():
        if isinstance(block, dict):
            out[f"models.{name}"] = {
                k: n for k, v in block.items()
                if (n := _numeric(v)) is not None
            }
    slo = doc.get("SLO") or {}
    for cls, block in (slo.get("classes") or {}).items():
        if isinstance(block, dict):
            out[f"slo.{cls}"] = {
                k: n for k, v in block.items()
                if (n := _numeric(v)) is not None
            }
    return out


def diff(
    old: Dict[str, Any], new: Dict[str, Any], threshold: float
) -> Tuple[List[Tuple[str, str, float, float, float, str]], int]:
    """Rows of (section, metric, old, new, rel_delta, flag); returns
    (rows, n_regressions). Only metrics present in BOTH rounds with a
    known direction are compared."""
    rows: List[Tuple[str, str, float, float, float, str]] = []
    regressions = 0
    old_secs, new_secs = _sections(old), _sections(new)
    for sec in sorted(set(old_secs) & set(new_secs)):
        o_blk, n_blk = old_secs[sec], new_secs[sec]
        for key in sorted(set(o_blk) & set(n_blk)):
            direction = _direction(key, section=sec)
            if direction is None:
                continue
            o, n = o_blk[key], n_blk[key]
            if o == 0 and n == 0:
                continue
            rel = (n - o) / abs(o) if o else float("inf")
            flag = ""
            if abs(rel) >= threshold:
                improved = (rel > 0) == (direction > 0)
                flag = "improved" if improved else "REGRESSED"
                if not improved:
                    regressions += 1
            rows.append((sec, key, o, n, rel, flag))
    return rows, regressions


def _fmt(value: float) -> str:
    if abs(value) >= 1000:
        return f"{value:.0f}"
    return f"{value:.4g}"


def render(
    rows: List[Tuple[str, str, float, float, float, str]],
    only_flagged: bool,
) -> str:
    shown = [r for r in rows if r[5]] if only_flagged else rows
    if not shown:
        return "no comparable metrics moved beyond threshold\n"
    headers = ("section", "metric", "old", "new", "delta", "")
    table = [
        (sec, key, _fmt(o), _fmt(n), f"{rel:+.1%}", flag)
        for sec, key, o, n, rel, flag in shown
    ]
    widths = [
        max(len(headers[i]), *(len(row[i]) for row in table))
        for i in range(6)
    ]
    lines = [
        "  ".join(h.ljust(widths[i]) for i, h in enumerate(headers)).rstrip()
    ]
    lines.append("  ".join("-" * w for w in widths))
    for row in table:
        lines.append(
            "  ".join(c.ljust(widths[i]) for i, c in enumerate(row)).rstrip()
        )
    return "\n".join(lines) + "\n"


def repo_latest_pair(root: Path) -> Tuple[Path, Path]:
    rounds = sorted(
        root.glob("BENCH_r*.json"),
        key=lambda p: int(re.search(r"r(\d+)", p.stem).group(1)),
    )
    if len(rounds) < 2:
        raise SystemExit(
            f"--repo-latest needs >= 2 BENCH_r*.json under {root} "
            f"(found {len(rounds)})"
        )
    return rounds[-2], rounds[-1]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("old", nargs="?", help="earlier round JSON")
    parser.add_argument("new", nargs="?", help="later round JSON")
    parser.add_argument(
        "--repo-latest", action="store_true",
        help="diff the two newest committed BENCH_r*.json in the repo root",
    )
    parser.add_argument(
        "--threshold", type=float, default=0.10,
        help="relative move that counts as a flagged change (default 0.10)",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="print every compared metric, not just flagged moves",
    )
    parser.add_argument(
        "--fail-on-regression", action="store_true",
        help="exit 1 when any metric regressed beyond threshold",
    )
    args = parser.parse_args(argv)

    if args.repo_latest:
        old_path, new_path = repo_latest_pair(Path(__file__).parent.parent)
    elif args.old and args.new:
        old_path, new_path = Path(args.old), Path(args.new)
    else:
        parser.error("give OLD.json NEW.json, or --repo-latest")
    old = json.loads(old_path.read_text())
    new = json.loads(new_path.read_text())
    rows, regressions = diff(old, new, args.threshold)
    print(f"bench diff: {old_path.name} -> {new_path.name} "
          f"(threshold {args.threshold:.0%})")
    print(render(rows, only_flagged=not args.all), end="")
    print(f"{regressions} regression(s) beyond threshold")
    if regressions and args.fail_on_regression:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
