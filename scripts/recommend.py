#!/usr/bin/env python3
"""Replay a workload profile through the cost model and recommend knobs.

The measurement loop (ISSUE 18): ``bench_slo`` runs under several knob
vectors produce *sample points* (knobs + measured steps/s, TTFT/TPOT
percentiles, attainment, per tagged workload — the AUTOCONF bench
section emits them, ``tests/fixtures/autoconf_samples.json`` is a
committed round), the serving deployment's workload profiler exports a
*fingerprint* (``/profile.json``, persisted into the profile store next
to ``autotune.json``), and this script closes the loop:

    python scripts/recommend.py                       # committed fixtures
    python scripts/recommend.py --samples S.json --profile P.json
    python scripts/recommend.py --deployment llama-tiny    # profile store
    python scripts/recommend.py --store               # persist the rec

It fits :class:`pilottai_tpu.obs.costmodel.CostModel` over the samples,
weights workloads by the profile's class mix, and prints the
recommended knob vector with predicted-vs-default deltas. With
``--store`` the recommendation lands in the profile store under the
deployment key, where the engine's boot check compares it against the
active config (``NativeEngine._warn_knob_divergence``).

Deterministic by construction (the model's tie-breaks are total
orders), and every recommended knob is validated against the modeled
bounds — the CI ``autoconf`` lane runs this twice over the committed
fixtures and gates on identical, in-bounds output.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from pilottai_tpu.obs.costmodel import CostModel, validate_knobs  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures"
DEFAULT_SAMPLES = FIXTURES / "autoconf_samples.json"
DEFAULT_PROFILE = FIXTURES / "autoconf_profile.json"


def _default_knobs(names):
    """Default value per knob name from LLMConfig's field defaults —
    the 'do nothing' configuration the recommendation is diffed
    against."""
    from pilottai_tpu.core.config import LLMConfig

    out = {}
    for name in sorted(names):
        field = LLMConfig.model_fields.get(name)
        if field is not None:
            out[name] = field.default
    return out


def _load_profile_blob(args) -> dict:
    if args.deployment:
        from pilottai_tpu.utils.compile_cache import load_profile

        blob = load_profile(args.deployment)
        if blob is None:
            raise SystemExit(
                f"no stored profile for deployment {args.deployment!r} "
                "(is the profile store populated?)"
            )
        return blob
    path = Path(args.profile) if args.profile else DEFAULT_PROFILE
    blob = json.loads(path.read_text())
    return blob


def recommend(samples_path: Path, profile_blob: dict) -> dict:
    model = CostModel.from_json(str(samples_path))
    fingerprint = profile_blob.get("fingerprint", profile_blob)
    knob_names = sorted({
        n for s in model.samples for n in s["knobs"]
    })
    default = _default_knobs(knob_names)
    rec = model.recommend(profile=fingerprint, default_knobs=default)
    if rec is None:
        raise SystemExit(f"no samples in {samples_path}")
    deployment = fingerprint.get("deployment")
    return {
        "deployment": deployment,
        "samples": len(model.samples),
        "workload_weights": fingerprint.get("class_mix", {}),
        **rec,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--samples", default=str(DEFAULT_SAMPLES),
                    help="recorded sample points (knobs+metrics JSON)")
    ap.add_argument("--profile", default=None,
                    help="profile fingerprint JSON (default: committed fixture)")
    ap.add_argument("--deployment", default=None,
                    help="read the profile from the profile store by key")
    ap.add_argument("--store", action="store_true",
                    help="persist the recommendation into the profile store")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw recommendation JSON only")
    args = ap.parse_args(argv)

    blob = _load_profile_blob(args)
    out = recommend(Path(args.samples), blob)

    if out["violations"]:
        print("RECOMMENDATION OUT OF BOUNDS:", file=sys.stderr)
        for v in out["violations"]:
            print(f"  {v}", file=sys.stderr)
        return 2

    if args.store:
        from pilottai_tpu.utils.compile_cache import load_profile, store_profile

        key = out["deployment"] or args.deployment
        if not key:
            print("--store needs a deployment key in the profile",
                  file=sys.stderr)
            return 2
        stored = load_profile(key) or {}
        stored["recommendation"] = {
            "knobs": out["knobs"], "score": out["score"],
            "predicted": out["predicted"],
        }
        store_profile(key, stored)

    if args.as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0

    print(f"deployment : {out['deployment']}")
    print(f"samples    : {out['samples']}")
    if out["workload_weights"]:
        print(f"class mix  : {out['workload_weights']}")
    print("recommended knobs:")
    for k, v in sorted(out["knobs"].items()):
        dflt = out.get("default_knobs", {}).get(k, "-")
        marker = "  " if v == dflt else "->"
        print(f"  {marker} {k:28s} {v!r:>10}   (default {dflt!r})")
    print("predicted (recommended vs default):")
    for k, v in sorted(out["predicted"].items()):
        dv = out.get("default_predicted", {}).get(k)
        delta = out.get("delta", {}).get(k)
        if dv is None:
            print(f"     {k:28s} {v:>10.4f}")
        else:
            print(f"     {k:28s} {v:>10.4f}  vs {dv:>10.4f}  "
                  f"(delta {delta:+.4f})")
    score = out["score"]
    print(f"score      : attainment={score['attainment']:.4f} "
          f"steps_per_s={score['steps_per_s']:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
